"""The device-program executor: ordered streams over a small worker
pool, plus the process-wide sharded-dispatch gate.

Model (docs/EXECUTOR.md):

  - A **stream** is a named FIFO: programs submitted to it execute one
    at a time, in submission order. Distinct streams interleave freely
    on the worker pool — that interleaving is where transfer/compute
    overlap comes from (GraphVite's episodic overlap, PAPERS.md).
  - A **program** is a host callable that typically ENQUEUES device
    work (JAX dispatch is asynchronous): snapshot under the server
    lock, revalidate coordinates, dispatch under the gate, release.
    Programs may also be pure host work (classification, batch prep).
  - **Edges**: `submit(..., after=[completion, ...])` orders a program
    behind programs on OTHER streams without any lock held across
    dispatch. Within a stream, FIFO is the edge.
  - The **dispatch gate** is one process-wide reentrant mutex around
    every sharded device-program dispatch. A sharded program on an
    N-virtual-device mesh enqueues onto N per-device execution queues;
    two lock domains dispatching concurrently can land their programs
    in different per-device orders, deadlocking XLA-CPU's collective
    rendezvous (the r10 known limit). Funneling every dispatch through
    the gate makes the per-device orders identical by construction —
    this IS the "one collective stream under all servers". The gate
    brackets only the enqueue (microseconds), never device execution.

Threading: workers are spawned lazily on first submission and park on
the executor's condvar when idle — an idle executor dispatches zero
device programs and burns zero CPU (pinned by
scripts/exec_overlap_check.py's idle guard).

Metrics (`exec.*`, schema_version 5; docs/OBSERVABILITY.md): per-stream
queue-depth gauges, an enqueue->dispatch latency histogram, program
counters, and the overlap_fraction gauge (fraction of busy wall time
where >= 2 streams were simultaneously active).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# the sharded-dispatch gate (the process-wide "collective stream")
# ---------------------------------------------------------------------------

# One gate per process, shared by every Server/store/runner regardless
# of which MeshContext it was built on: in-process device sets always
# share the same XLA backend (and its per-device execution queues), so
# one gate covers every combination of servers that could interleave.
# Reentrant: store ops nest (tiered gather -> cold-path program) and a
# caller already holding the gate must not self-deadlock. The RLock
# lives inside a SentinelLock (lint/lockorder.py): dispatch sites
# capture the gate at import (`_GATE = dispatch_gate()`), so the
# lock-order sentinel cannot swap it per server the way it swaps
# Server._lock — instead the wrapper pays the r7 skip-wrapper price,
# one `is None` check per acquire when the sentinel is off
# (--sys.lint.lockorder, default), full leaf/cycle edge recording
# when it is on.
from ..lint.lockorder import GATE_NAME, GATE_UID, SentinelLock

_DISPATCH_GATE = SentinelLock(GATE_NAME, uid=GATE_UID)


def dispatch_gate() -> "SentinelLock":
    """The process-wide sharded-dispatch mutex. Every site that
    dispatches a sharded device program acquires it around the dispatch
    (enqueue) itself — `with dispatch_gate(): self.main = _prog(...)`.
    Held for the enqueue only; never across device execution, network
    waits, or the server lock (it is a LEAF lock — mechanically
    enforced by adapm-lint APM001/APM002 and, at runtime, by the
    --sys.lint.lockorder sentinel; docs/INVARIANTS.md)."""
    return _DISPATCH_GATE


# ---------------------------------------------------------------------------
# completions + programs
# ---------------------------------------------------------------------------


class Completion:
    """Handle for one submitted program: wait / result / error. Stream
    edges are expressed by passing completions as `after=`."""

    __slots__ = ("label", "_event", "_result", "error", "cancelled")

    def __init__(self, label: str = ""):
        self.label = label
        self._event = threading.Event()
        self._result = None
        self.error: Optional[BaseException] = None
        self.cancelled = False

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"program {self.label!r} did not "
                               f"complete within {timeout}s")
        if self.error is not None:
            raise self.error
        return self._result

    def _finish(self, result=None, error: Optional[BaseException] = None,
                cancelled: bool = False) -> None:
        self._result = result
        self.error = error
        self.cancelled = cancelled
        self._event.set()


def _done_completion(label: str = "") -> Completion:
    c = Completion(label)
    c._finish(cancelled=True)
    return c


class _Program:
    __slots__ = ("fn", "label", "coalesce_key", "after", "not_before",
                 "t_submit", "completion", "attempts")

    def __init__(self, fn, label, coalesce_key, after, not_before):
        self.fn = fn
        self.label = label
        self.coalesce_key = coalesce_key
        self.after = tuple(after)
        self.not_before = not_before
        self.t_submit = time.monotonic()
        self.completion = Completion(label)
        # transient-failure retries consumed so far (fault/policy.py):
        # the SAME program object re-queues at its stream head, so the
        # completion stays open until the final outcome
        self.attempts = 0

    def ready(self, now: float) -> bool:
        if self.not_before > now:
            return False
        return all(c.done() for c in self.after)


class _Stream:
    __slots__ = ("name", "q", "active", "busy_since", "busy_label")

    def __init__(self, name: str):
        self.name = name
        self.q: "collections.deque[_Program]" = collections.deque()
        # active > 0 while a program of this stream executes (queued
        # ones hold exactly 1; inline `track` sections add theirs)
        self.active = 0
        # wall-clock start + label of the QUEUED program currently
        # executing (None = none). Written by the owning worker under
        # _cond; the watchdog probe (wedged_streams) reads it to flag
        # a program busy past --sys.fault.watchdog_s without ever
        # blocking behind it.
        self.busy_since = None
        self.busy_label = None


# ---------------------------------------------------------------------------


class AsyncExecutor:
    """Ordered-stream program executor over a bounded worker pool (see
    module docstring; one per Server, `Server.exec`).

    `single_stream=True` is the serialized fallback (--sys.exec.
    single_stream): the worker pool shrinks to ONE thread, so
    background programs execute strictly one at a time (oldest
    submission first — global FIFO whenever programs are eligible) and
    cross-stream overlap is zero. Streams KEEP their identity: per-
    subsystem drains still drain just that subsystem, and a delayed
    program (e.g. the prefetch window poll) blocks only its own stream,
    never an admitted serve drain behind it. This is the baseline the
    bench's `exec` phase and exec_overlap_check.py compare the
    overlapped default against, and the conservative escape hatch.
    """

    def __init__(self, registry=None, workers: int = 4,
                 single_stream: bool = False, name: str = "exec",
                 recorder=None, retry_policy=None, fault=None):
        self.name = name
        # optional flight recorder (obs/flight.py, rides
        # --sys.crash_dumps): one ring append + pwrite per PROGRAM —
        # never per Pull/Push op, so the hot path never sees it
        self.recorder = recorder
        # executor error policy (ISSUE 10; fault/policy.py): transient
        # program failures re-queue at the head of their stream with
        # bounded exponential backoff instead of killing the waiter /
        # the subsystem's self-rescheduling loop. None (or the default
        # classifier with nothing raising TransientFaultError) is
        # byte-for-byte the pre-policy behavior.
        self.retry_policy = retry_policy
        # optional fault-injection plane (fault/inject.py): fires the
        # exec.dispatch (retry-safe, before the program runs) and
        # exec.complete (FATAL — the work already happened) points.
        # None costs one attribute check per program, never per op.
        self.fault = fault
        # streams currently flagged wedged by the watchdog probe (the
        # flip counter increments on the not-wedged -> wedged edge)
        self._wedged_known: set = set()
        self.max_workers = 1 if single_stream else max(1, int(workers))
        self.single_stream = bool(single_stream)
        self._cond = threading.Condition()
        self._streams: Dict[str, _Stream] = {}
        self._threads: List[threading.Thread] = []
        self._closed = False
        self._idle_workers = 0
        # ---- accounting (all under _cond) ----
        self._n_active_streams = 0
        self._acct_t = time.monotonic()
        # busy-wall-time buckets keyed by concurrent-stream count:
        # [idle, single, overlap(>=2)]
        self._t_buckets = [0.0, 0.0, 0.0]
        self._started = 0
        self._finished = 0
        # ---- metrics (exec.* section, docs/OBSERVABILITY.md) ----
        self._registry = registry
        from ..obs.metrics import Counter, Histogram
        use_reg = registry is not None and registry.enabled
        if use_reg:
            self._c_programs = registry.counter("exec.programs_total")
            self._h_wait = registry.histogram("exec.dispatch_wait_s")
            registry.gauge("exec.overlap_fraction",
                           fn=self.overlap_fraction)
            registry.gauge("exec.queue_depth", fn=self.queue_depth)
            registry.gauge("exec.streams", fn=lambda: len(self._streams))
            registry.gauge("exec.workers", fn=lambda: len(self._threads))
            registry.gauge("exec.inflight",
                           fn=lambda: self._started - self._finished)
        else:
            self._c_programs = Counter("exec.programs_total")
            self._h_wait = Histogram("exec.dispatch_wait_s")
        # watchdog flip counter: standalone on purpose — it reaches the
        # snapshot through stats()/the fault section, and the registry
        # must hold zero fault.* names when injection is off
        self._c_wedge_flips = Counter("exec.wedge_flips")

    # -- accounting ----------------------------------------------------------

    def _account(self) -> None:
        """Fold elapsed wall time into the bucket of the CURRENT
        concurrent-stream count; callers mutate the count right after.
        Caller holds _cond."""
        now = time.monotonic()
        n = self._n_active_streams
        self._t_buckets[2 if n >= 2 else n] += now - self._acct_t
        self._acct_t = now

    def _stream_enter(self, st: _Stream) -> None:
        if st.active == 0:
            self._account()
            self._n_active_streams += 1
        st.active += 1

    def _stream_exit(self, st: _Stream) -> None:
        st.active -= 1
        if st.active == 0:
            self._account()
            self._n_active_streams -= 1

    def overlap_fraction(self) -> float:
        """Fraction of BUSY executor wall time where >= 2 streams were
        simultaneously active (the GraphVite-style overlap measure: >0
        means host prep / staging genuinely ran while another stream's
        device program was in flight)."""
        with self._cond:
            self._account()
            single, over = self._t_buckets[1], self._t_buckets[2]
        busy = single + over
        return over / busy if busy else 0.0

    def queue_depth(self, stream: Optional[str] = None) -> int:
        with self._cond:
            if stream is not None:
                st = self._streams.get(stream)
                return len(st.q) if st is not None else 0
            return sum(len(s.q) for s in self._streams.values())

    def stats(self) -> Dict[str, float]:
        with self._cond:
            self._account()
            idle, single, over = self._t_buckets
            return {"programs_started": self._started,
                    "programs_finished": self._finished,
                    "queued": sum(len(s.q) for s in self._streams.values()),
                    "streams": len(self._streams),
                    "workers": len(self._threads),
                    "busy_s": single + over,
                    "overlap_s": over,
                    "overlap_fraction": over / (single + over)
                    if (single + over) else 0.0,
                    "retries": int(self.retry_policy.c_retries.value)
                    if self.retry_policy is not None else 0,
                    "wedge_flips": int(self._c_wedge_flips.value)}

    def wedged_streams(self, bound_s: float,
                       exclude=()) -> List[Dict]:
        """Streams whose CURRENT program has been executing longer than
        `bound_s` — the per-program watchdog (ISSUE 10): a wedged
        program cannot be interrupted (its thread is stuck inside the
        callable), but it can be NAMED, so readiness flips and waiters
        fail-stop on their own bounds instead of the whole process
        hanging silently. Reads the busy stamps under the executor
        condvar (brief; the wedged program holds no executor lock while
        running, so this probe never blocks behind it). Each
        not-wedged -> wedged edge counts one wedge flip. `exclude`
        names streams whose programs are LEGITIMATELY long-running
        loops with their own finer-grained liveness probe (the serve
        drains: one program serves batches until its lane empties, and
        LookupBatcher.wedged_dispatchers bounds each BATCH instead)."""
        now = time.monotonic()
        out: List[Dict] = []
        skip = set(exclude)
        with self._cond:
            for st in self._streams.values():
                if st.name in skip:
                    continue
                t = st.busy_since
                if t is not None and now - t > bound_s:
                    out.append({"stream": st.name,
                                "label": st.busy_label,
                                "busy_s": now - t})
                    if st.name not in self._wedged_known:
                        self._wedged_known.add(st.name)
                        self._c_wedge_flips.inc()
                elif st.name in self._wedged_known and (
                        t is None or now - t <= bound_s):
                    self._wedged_known.discard(st.name)
        return out

    def fault_stats(self) -> Dict[str, float]:
        """The executor's half of the `fault` snapshot section:
        retry/backoff totals (fault/policy.py) + watchdog flips."""
        out: Dict[str, float] = {
            "wedge_flips": int(self._c_wedge_flips.value)}
        if self.retry_policy is not None:
            out.update(self.retry_policy.stats())
        return out

    # -- submission ----------------------------------------------------------

    def _get_stream(self, name: str) -> _Stream:
        st = self._streams.get(name)
        if st is None:
            st = self._streams[name] = _Stream(name)
            reg = self._registry
            if reg is not None and reg.enabled:
                reg.gauge(f"exec.queue_depth.{name}", shared=True,
                          fn=lambda n=name: self.queue_depth(n))
        return st

    def submit(self, stream: str, fn: Callable[[], object],
               label: Optional[str] = None, coalesce_key: Optional[str]
               = None, delay: float = 0.0, after=()) -> Completion:
        """Enqueue `fn` on `stream`. FIFO within the stream; `after`
        completions (from any stream) must be done before it starts;
        `delay` postpones eligibility (timer work without a sleeping
        thread). `coalesce_key`: if a not-yet-started program with the
        same key is already queued on the stream, no new program is
        added — the existing completion is returned with its
        eligibility tightened to min(existing, now+delay). Safe to call
        under subsystem locks (the executor lock is a leaf).

        After close(): returns an already-completed (cancelled)
        completion — late kicks during teardown are no-ops, never
        crashes."""
        nb = time.monotonic() + max(0.0, delay)
        with self._cond:
            if self._closed:
                return _done_completion(label or "closed")
            st = self._get_stream(stream)
            if coalesce_key is not None:
                for p in st.q:
                    if p.coalesce_key == coalesce_key:
                        if nb < p.not_before:
                            p.not_before = nb
                            self._cond.notify_all()
                        return p.completion
            prog = _Program(fn, label or getattr(fn, "__name__", "?"),
                            coalesce_key, after, nb)
            st.q.append(prog)
            self._ensure_worker()
            self._cond.notify_all()
            return prog.completion

    def track(self, stream: str):
        """Accounting-only context for INLINE dispatch (fused steps and
        other caller-thread programs): marks `stream` active for the
        overlap/occupancy gauges while the caller dispatches. No FIFO
        claim — inline callers serialize through the server lock, and
        their sharded dispatch goes through the gate like everything
        else."""
        return _InlineTrack(self, stream)

    # -- draining / lifecycle ------------------------------------------------

    def drain(self, stream: Optional[str] = None,
              timeout: Optional[float] = None) -> bool:
        """Block until `stream` (or every stream) has no queued and no
        executing program. Returns False on timeout. Does NOT prevent
        new submissions — callers stop their producers first."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        name = stream
        with self._cond:
            while True:
                if name is None:
                    idle = all(len(s.q) == 0 and s.active == 0
                               for s in self._streams.values())
                else:
                    st = self._streams.get(name)
                    idle = st is None or (len(st.q) == 0
                                          and st.active == 0)
                if idle:
                    return True
                if deadline is None:
                    self._cond.wait(0.5)
                else:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        return False
                    self._cond.wait(min(rem, 0.5))

    def drain_streams(self, streams, timeout: Optional[float] = None) \
            -> bool:
        """Drain several streams under ONE shared deadline (the serve
        plane's N dispatcher streams must all quiesce within the same
        bound at stop time — N sequential per-stream timeouts would
        multiply the worst-case teardown wait). Returns False when the
        deadline expires with any stream still busy."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        for name in streams:
            rem = None if deadline is None else \
                max(0.0, deadline - time.monotonic())
            if not self.drain(name, timeout=rem):
                return False
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Idempotent shutdown: cancel not-yet-started programs (their
        completions finish cancelled — no waiter hangs), let running
        ones finish, join the workers. Server.shutdown() calls this
        LAST, after every producer subsystem has been stopped, so a
        well-ordered teardown cancels nothing."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            for st in self._streams.values():
                while st.q:
                    st.q.popleft().completion._finish(cancelled=True)
            self._cond.notify_all()
            threads = list(self._threads)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        alive = [t.name for t in threads if t.is_alive()]
        if alive:
            from ..utils import alog
            alog(f"[exec] workers failed to exit within {timeout}s: "
                 f"{alive} — a program is wedged mid-dispatch")

    @property
    def closed(self) -> bool:
        return self._closed

    def live_streams(self) -> List[str]:
        """Streams with queued or executing programs (empty after a
        clean close — the 'no orphaned streams' shutdown assertion)."""
        with self._cond:
            return sorted(s.name for s in self._streams.values()
                          if s.q or s.active)

    # -- workers -------------------------------------------------------------

    def _ensure_worker(self) -> None:
        """Spawn a worker if every existing one is busy and we are under
        the cap (caller holds _cond). Lazy: an executor that is never
        submitted to owns zero threads."""
        if self._idle_workers == 0 and \
                len(self._threads) < self.max_workers:
            t = threading.Thread(
                target=self._worker, daemon=True,
                name=f"adapm-{self.name}-{len(self._threads)}")
            self._threads.append(t)
            t.start()

    def _pick_locked(self, now: float):
        """(program, stream) of the oldest eligible head-of-stream, or
        (None, soonest_not_before). FIFO per stream: only each stream's
        HEAD is a candidate, and a head blocked on `after`/`not_before`
        blocks its whole stream (that is what 'ordered' means)."""
        best = None
        best_stream = None
        soonest = None
        for st in self._streams.values():
            if st.active or not st.q:
                continue
            head = st.q[0]
            if head.not_before > now:
                soonest = head.not_before if soonest is None else \
                    min(soonest, head.not_before)
                continue
            if not all(c.done() for c in head.after):
                # dep from another executor/track would not notify us:
                # poll soon rather than parking forever
                soonest = now + 0.05 if soonest is None else \
                    min(soonest, now + 0.05)
                continue
            if best is None or head.t_submit < best.t_submit:
                best, best_stream = head, st
        return (best, best_stream) if best is not None else (None, soonest)

    def _worker(self) -> None:
        from ..utils import alog
        while True:
            with self._cond:
                while True:
                    if self._closed:
                        return
                    now = time.monotonic()
                    prog, st_or_soonest = self._pick_locked(now)
                    if prog is not None:
                        st = st_or_soonest
                        break
                    self._idle_workers += 1
                    try:
                        # park on the condvar: None timeout unless a
                        # delayed program needs a timed wake
                        soonest = st_or_soonest
                        self._cond.wait(
                            None if soonest is None
                            else max(0.0, soonest - now))
                    finally:
                        self._idle_workers -= 1
                st.q.popleft()
                self._stream_enter(st)
                self._started += 1
                st.busy_since = time.monotonic()
                st.busy_label = prog.label
            self._c_programs.inc()
            t_run = time.monotonic()
            wait_s = t_run - prog.t_submit
            self._h_wait.observe(wait_s)
            result = None
            error = None
            try:
                f = self.fault
                if f is not None:
                    # retry-safe point: fires BEFORE the program runs,
                    # so a retried attempt re-executes from scratch
                    f.fire("exec.dispatch")
                result = prog.fn()
                if f is not None:
                    # completion-side point: the work already happened,
                    # only the completion is lost — FATAL by
                    # construction (a retry would double-execute)
                    f.fire("exec.complete", transient=False)
            except BaseException as e:  # noqa: BLE001 — the pool must
                # outlive any one program; the error reaches waiters
                # via the completion and the log
                error = e
                alog(f"[exec] program {prog.label!r} on stream "
                     f"{st.name!r} failed: {type(e).__name__}: {e}")
            rec = self.recorder
            if rec is not None:
                rec.record(st.name, prog.label, prog.coalesce_key,
                           wait_s, time.monotonic() - t_run,
                           failed=error is not None)
            # error policy (fault/policy.py): a TRANSIENT failure with
            # budget left re-queues the SAME program at its stream head
            # (FIFO preserved) after an exponential backoff; the
            # completion stays open until the final outcome
            pol = self.retry_policy
            if (error is not None and pol is not None
                    and prog.attempts < pol.max_retries
                    and pol.classify(error)):
                prog.attempts += 1
                delay = pol.backoff_s(prog.attempts)
                pol.c_retries.inc()
                pol.c_backoff_s.inc(delay)
                alog(f"[exec] retrying {prog.label!r} on stream "
                     f"{st.name!r} (attempt {prog.attempts}/"
                     f"{pol.max_retries}, backoff {delay * 1e3:.0f} ms)")
                with self._cond:
                    self._stream_exit(st)
                    self._finished += 1
                    st.busy_since = None
                    st.busy_label = None
                    if self._closed:
                        # teardown won the race: finish cancelled, no
                        # waiter hangs on a retry that can never run
                        prog.completion._finish(cancelled=True)
                    else:
                        prog.not_before = time.monotonic() + delay
                        st.q.appendleft(prog)
                    self._cond.notify_all()
                continue
            with self._cond:
                self._stream_exit(st)
                self._finished += 1
                st.busy_since = None
                st.busy_label = None
                self._cond.notify_all()
            prog.completion._finish(result, error)


class _InlineTrack:
    __slots__ = ("ex", "name", "_st")

    def __init__(self, ex: AsyncExecutor, name: str):
        self.ex = ex
        self.name = name
        self._st = None

    def __enter__(self):
        ex = self.ex
        with ex._cond:
            if not ex._closed:
                self._st = ex._get_stream(self.name)
                ex._stream_enter(self._st)
        return self

    def __exit__(self, *exc):
        ex = self.ex
        with ex._cond:
            if self._st is not None:
                ex._stream_exit(self._st)
                ex._cond.notify_all()
        return False
