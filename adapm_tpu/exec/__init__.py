"""Unified async executor (ISSUE 6 tentpole): ONE program-dispatch
plane under all five subsystems.

Before this package, five subsystems each owned a thread + lock bracket
that enqueued device programs — sync rounds (core/sync.py), prefetch
staging (core/intent.py), tier promotion/demotion (tier/promote.py),
serve gathers (serve/batcher.py), and fused steps (ops/fused.py). The
seams showed: two servers sharing one virtual device set could deadlock
XLA-CPU's collective rendezvous because no single owner controlled
enqueue order across lock domains (the r10 known limit).

The executor provides (docs/EXECUTOR.md has the full contract):

  - **ordered streams per resource** (`AsyncExecutor`): programs
    submitted to one stream run FIFO, one at a time; distinct streams
    interleave freely; dependencies are expressed as stream edges
    (`after=` completions), never as a lock held across dispatch;
  - **sharded-dispatch serialization** (`dispatch_gate`): every sharded
    device-program dispatch in the process funnels through one gate —
    the process-wide "collective stream" — so programs land on every
    device of the set in ONE global order, eliminating the rendezvous
    deadlock by construction;
  - **overlap**: background host work (promotion batch prep, prefetch
    staging, sync classification) runs on executor streams while device
    programs dispatched from other streams are in flight, with an
    `exec.overlap_fraction` gauge measuring the wall time where >= 2
    streams were simultaneously busy.

The lock-narrowing rule, stated once: **enqueue under the lock,
dispatch never** — the server lock brackets table snapshots, coordinate
revalidation, and stream/program ENQUEUE; the executor (and JAX's async
dispatch under the gate) owns execution order.
"""
from .executor import (AsyncExecutor, Completion,  # noqa: F401
                       dispatch_gate)
