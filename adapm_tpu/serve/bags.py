"""Embedding-bag serving support (ISSUE 16 tentpole a): the request
type, the batch planner, and the host pooling twin.

A *bag read* asks for POOLED vectors — per table, `bags` offsets
partition that table's member keys into segments and the reply is one
sum- or mean-pooled vector per segment (`ServeSession.lookup_bags`).
DLRM-style inference is dominated by exactly this access pattern
("Dissecting Embedding Bag Performance in DLRM Inference", PAPERS.md):
pooling on the host after a flat gather ships every member row over
the device boundary only to reduce it immediately, so the fused path
dispatches `ShardedStore.gather_pool` — gather + segment-reduce in ONE
device program per (length class, pooling) — and only the pooled
vectors cross.

Bit-identity contract: the fused program accumulates member rows in
batch order (`jaxport._pool_rows`, the same `.at[].add` contract the
coldpath relies on), and `pool_bags_host` below accumulates with
`np.add.at` in the same member order — the two are bit-identical for
every batch, which is what lets the batcher pick per dispatch (replica
snapshot → host pool; locked path → fused device pool; multi-process
or `--sys.serve.bags 0` → flat union gather + host pool) without the
choice ever being observable in the returned bits
(scripts/portdiff_check.py pins this across ports).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .admission import LookupRequest


class BagLookupRequest(LookupRequest):
    """One client bag lookup riding the same admission queue / claim
    machinery as a flat `LookupRequest`. `keys` is the flat concat of
    every table's member keys (what admission, lane assignment, and
    union dedup see); `tables`/`bags` keep the per-table structure the
    pooling needs. Delivery carries the flat concat of the per-table
    pooled matrices (`[nbags_t, L_t]` row-major, tables in order) —
    the session reshapes."""

    __slots__ = ("tables", "bags", "pooling")

    def __init__(self, tables: Sequence[np.ndarray],
                 bags: Sequence[np.ndarray], pooling: str,
                 keys: np.ndarray, **kw):
        super().__init__(keys, **kw)
        self.tables = list(tables)
        self.bags = list(bags)
        self.pooling = pooling


def pool_bags_host(rows: np.ndarray, seg: np.ndarray, nbags: int,
                   pooling: str) -> np.ndarray:
    """Pool member `rows` [n, L] into [nbags, L] on the host — the
    bit-identical twin of the device program (module docstring):
    batch-order `np.add.at` sum, then for mean ONE division per bag
    (empty bags pool to exact zeros, matching the device masked
    divide)."""
    rows = np.asarray(rows)
    seg = np.asarray(seg)
    out = np.zeros((int(nbags), rows.shape[1]), dtype=rows.dtype)
    np.add.at(out, seg, rows)
    if pooling == "sum":
        return out
    cnt = np.zeros(int(nbags), dtype=rows.dtype)
    np.add.at(cnt, seg, rows.dtype.type(1))
    denom = np.where(cnt > 0, cnt, rows.dtype.type(1))[:, None]
    return np.where(cnt[:, None] > 0, out / denom, np.zeros_like(out))


# a group key is (length-class id, pooling) — one device program (or
# one host pool) per group serves every request's tables in that group
GroupKey = Tuple[int, str]


def plan_bag_batch(reqs: List[BagLookupRequest], key_class: np.ndarray):
    """Coalesce a batch of bag requests into per-(class, pooling)
    groups. Returns `(groups, slices)`:

      groups[gkey] = {"keys": member keys (concat, REQUEST ORDER —
                      the order the pooling accumulates in), "seg":
                      int32 global bag index per member, "nbags": int}
      slices[i]    = [(gkey, bag_start, nbags_t), ...] per request i's
                     tables, in table order — slice the group's pooled
                     matrix `[bag_start : bag_start + nbags_t]` to get
                     that table's reply.

    Member DUPLICATES are preserved (each member position is one
    accumulation entry — dedup here would change the pooled sums);
    union dedup for replica-coverage/metrics happens on the caller's
    side over `req.keys`."""
    groups: Dict[GroupKey, dict] = {}
    slices: List[list] = []
    for r in reqs:
        rs = []
        for ks, bg in zip(r.tables, r.bags):
            gkey = (int(key_class[ks[0]]), r.pooling)
            g = groups.setdefault(gkey,
                                  {"keys": [], "seg": [], "nbags": 0})
            nb = len(bg) - 1
            seg = (np.repeat(np.arange(nb, dtype=np.int64),
                             np.diff(bg)).astype(np.int32) + g["nbags"])
            g["keys"].append(ks)
            g["seg"].append(seg)
            rs.append((gkey, g["nbags"], nb))
            g["nbags"] += nb
        slices.append(rs)
    for g in groups.values():
        g["keys"] = np.concatenate(g["keys"])
        g["seg"] = np.concatenate(g["seg"]).astype(np.int32) \
            if g["seg"] else np.empty(0, np.int32)
    return groups, slices
