"""Online serving plane (ISSUE 4 tentpole): the PM as a query-servable
store.

Training built the store; this layer reads it under load. The pieces
(each in its own module, docs/SERVING.md has the user guide):

  - `admission` — bounded request queue with backpressure + deadlines
    (reject loudly, never hang);
  - `batcher`  — micro-batching coalescer: concurrent lookups merge
    into one deduplicated key batch dispatched as a single fused gather
    per length class through the routing-plan cache;
  - `session`  — the client API: `ServeSession.lookup(keys,
    deadline_ms)`, snapshot-consistent and bit-identical to a plain
    `Worker.pull`, including read-your-writes for clients that push;
  - `health`   — liveness/readiness folding `Server.dead_nodes` and
    queue depth into `metrics_snapshot()` (serve section, schema v3).

Quickstart::

    from adapm_tpu.serve import ServePlane
    plane = ServePlane(server)            # knobs from server.opts
    sess = plane.session()                # one per client thread
    vals = sess.lookup(keys, deadline_ms=50)
    plane.close()                         # or rely on server.shutdown()
"""
from __future__ import annotations

from .admission import (AdmissionQueue, DeadlineExceededError,  # noqa: F401
                        LookupRequest, ServeOverloadError)
from .batcher import LookupBatcher  # noqa: F401
from .health import HealthMonitor  # noqa: F401
from .session import ServeSession  # noqa: F401


class ServePlane:
    """Assembles queue + batcher + health over one Server and owns their
    lifecycle. One live plane per Server (the serve.* metrics namespace
    is single-registration; a plane closed and rebuilt on the same
    server reuses it — gauges rebind to the new plane)."""

    def __init__(self, server, opts=None, shard: int = 0,
                 start: bool = True, dead_nodes_fn=None,
                 dead_node_max_age_s: float = 10.0):
        opts = opts if opts is not None else server.opts
        opts.validate_serve()  # fail loudly on bad knobs, even when the
        # options object was hand-built rather than parsed
        if getattr(server, "_serve_plane", None) is not None:
            raise RuntimeError(
                "one live ServePlane per Server: close() the existing "
                "plane first")
        self.server = server
        self.opts = opts
        self.queue = AdmissionQueue(opts.serve_queue, registry=server.obs)
        self.batcher = LookupBatcher(server, opts, self.queue, shard=shard)
        self.health = HealthMonitor(self, max_age_s=dead_node_max_age_s,
                                    dead_nodes_fn=dead_nodes_fn)
        # SLO autopilot (obs/slo.py, ISSUE 7): only with a target set —
        # unset, no controller exists and the static max_wait_us knob
        # path is untouched (the module is not even imported)
        self.slo = None
        if opts.serve_slo_ms > 0:
            from ..obs.slo import SLOController
            self.slo = SLOController(server, self.batcher,
                                     target_ms=opts.serve_slo_ms)
        server._serve_plane = self
        if start:
            self.start()

    def start(self) -> None:
        self.batcher.start()
        if self.slo is not None:
            self.slo.start()

    def session(self, worker=None) -> ServeSession:
        """A client handle (one per client thread; cheap). Pass the
        client's `Worker` for cross-process read-your-writes ordering."""
        return ServeSession(self, worker=worker)

    def close(self) -> None:
        """Stop the dispatcher and fail-stop queued requests. Idempotent;
        also called by `Server.shutdown()`."""
        if self.slo is not None:
            # stop the control loop before the dispatcher: a tick that
            # already sits queued on the `slo` stream sees _closed and
            # exits (executor close cancels it outright)
            self.slo.close()
        self.batcher.stop()
        if getattr(self.server, "_serve_plane", None) is self:
            self.server._serve_plane = None

    def __enter__(self) -> "ServePlane":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
