"""Online serving plane (ISSUE 4 tentpole; ISSUE 9 read fast path +
tenancy): the PM as a query-servable store.

Training built the store; this layer reads it under load. The pieces
(each in its own module, docs/SERVING.md has the user guide):

  - `admission` — bounded request lanes with backpressure + deadlines
    (reject loudly, never hang), per-tenant token-bucket quotas and
    priority classes (shed low-priority first under pressure,
    fair-share the batch budget across tenants);
  - `batcher`  — micro-batching coalescer: concurrent lookups merge
    into one deduplicated key batch dispatched as a single fused gather
    per length class through the routing-plan cache, on
    `--sys.serve.dispatchers` sharded dispatcher streams;
  - `replica`  — the read-only hot-row fast path: an epoch-versioned
    snapshot served WITHOUT the server lock, bit-identical by write-
    epoch validation (`--sys.serve.replica_rows`);
  - `session`  — the client API: `ServeSession.lookup(keys,
    deadline_ms)`, snapshot-consistent and bit-identical to a plain
    `Worker.pull`, including read-your-writes for clients that push;
  - `health`   — liveness/readiness folding `Server.dead_nodes`,
    per-dispatcher wedge detection, and queue depth into
    `metrics_snapshot()` (serve section).

Quickstart::

    from adapm_tpu.serve import ServePlane
    plane = ServePlane(server)            # knobs from server.opts
    plane.configure_tenant("gold", priority=1)          # optional QoS
    plane.configure_tenant("bronze", priority=0, qps=500)
    sess = plane.session(tenant="gold")   # one per client thread
    vals = sess.lookup(keys, deadline_ms=50)
    plane.close()                         # or rely on server.shutdown()
"""
from __future__ import annotations

from .admission import (AdmissionQueue, DeadlineExceededError,  # noqa: F401
                        LookupRequest, ServeDegradedError,
                        ServeOverloadError, TenantState)
from .batcher import LookupBatcher  # noqa: F401
from .health import HealthMonitor  # noqa: F401
from .replica import ServeReplica  # noqa: F401
from .session import ServeSession  # noqa: F401


class ServePlane:
    """Assembles lanes + batcher + replica + health over one Server and
    owns their lifecycle. One live plane per Server (the serve.* metrics
    namespace is single-registration; a plane closed and rebuilt on the
    same server reuses it — gauges rebind to the new plane)."""

    def __init__(self, server, opts=None, shard: int = 0,
                 start: bool = True, dead_nodes_fn=None,
                 dead_node_max_age_s: float = 10.0):
        opts = opts if opts is not None else server.opts
        opts.validate_serve()  # fail loudly on bad knobs, even when the
        # options object was hand-built rather than parsed
        if getattr(server, "_serve_plane", None) is not None:
            raise RuntimeError(
                "one live ServePlane per Server: close() the existing "
                "plane first")
        self.server = server
        self.opts = opts
        self.queue = AdmissionQueue(opts.serve_queue, registry=server.obs,
                                    lanes=max(1, opts.serve_dispatchers),
                                    lockorder=getattr(
                                        opts, "lint_lockorder", False))
        self.batcher = LookupBatcher(server, opts, self.queue, shard=shard)
        # read-only serve replica (ISSUE 9 tentpole a; serve/replica.py):
        # only with rows budgeted — unset, every lookup takes the exact
        # locked path and the replica metrics stay present-but-inert
        self.replica = None
        if opts.serve_replica_rows > 0:
            self.replica = ServeReplica(server, opts,
                                        registry=server.obs)
            self.batcher.replica = self.replica
        self.health = HealthMonitor(self, max_age_s=dead_node_max_age_s,
                                    dead_nodes_fn=dead_nodes_fn)
        # SLO autopilot (obs/slo.py, ISSUE 7): only with a target set —
        # unset, no controller exists and the static max_wait_us knob
        # path is untouched (the module is not even imported)
        self.slo = None
        if opts.serve_slo_ms > 0:
            from ..config import parse_class_targets
            from ..obs.slo import SLOController
            # per-priority-class overrides (ISSUE 20 satellite;
            # `--sys.serve.slo_ms 20,1=5`): validated at parse time,
            # re-parsed here into {priority: target_ms}
            cls = parse_class_targets(opts.serve_slo_ms,
                                      opts.serve_slo_class,
                                      flag="--sys.serve.slo_ms")
            self.slo = SLOController(server, self.batcher,
                                     target_ms=opts.serve_slo_ms,
                                     class_targets=cls)
        server._serve_plane = self
        if start:
            self.start()

    def start(self) -> None:
        self.batcher.start()
        if self.slo is not None:
            self.slo.start()

    def configure_tenant(self, name: str, priority: int = 0,
                         qps: float = 0.0, burst=None) -> TenantState:
        """Create or update a tenant's admission policy (token-bucket
        quota + priority class; serve/admission.py). Idempotent —
        reconfiguring a live tenant adjusts its policy in place."""
        return self.queue.configure_tenant(name, priority=priority,
                                           qps=qps, burst=burst)

    def session(self, worker=None, tenant=None,
                priority=None) -> ServeSession:
        """A client handle (one per client thread; cheap). Pass the
        client's `Worker` for cross-process read-your-writes ordering;
        `tenant`/`priority` bind the session to an admission class
        (docs/SERVING.md "Read fast path & tenancy")."""
        return ServeSession(self, worker=worker, tenant=tenant,
                            priority=priority)

    def close(self) -> None:
        """Stop the dispatchers and fail-stop queued requests.
        Idempotent; also called by `Server.shutdown()`."""
        if self.slo is not None:
            # stop the control loop before the dispatchers: a tick that
            # already sits queued on the `slo` stream sees _closed and
            # exits (executor close cancels it outright)
            self.slo.close()
        if self.replica is not None:
            # the refresh program reads through the pools like a
            # dispatcher drain: quiesce it before teardown proceeds
            self.replica.close()
        self.batcher.stop()
        if getattr(self.server, "_serve_plane", None) is self:
            self.server._serve_plane = None

    def __enter__(self) -> "ServePlane":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
