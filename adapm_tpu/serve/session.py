"""Client-facing serving API: `ServeSession.lookup(keys, deadline_ms)`.

A session is a lightweight per-client handle onto a ServePlane. It is
thread-compatible the way a `Worker` is: one client thread per session
(sessions are cheap — make one per thread). `lookup` submits into the
admission queue (raising `ServeOverloadError` under backpressure) and
blocks until the coalescing dispatcher delivers the values or the
deadline sheds the request.

Tenancy (ISSUE 9): a session constructed with `tenant=` (a name) and
optionally `priority=` stamps every lookup with that tenant's admission
state — its token-bucket quota gates submit (`ServeOverloadError` when
the bucket is dry), its priority class decides who sheds first under
pressure and who the fair-share batch budget favors
(serve/admission.py). `ServePlane.configure_tenant` sets the policy; a
session naming an unconfigured tenant gets an unthrottled priority-0
default. With no tenant the request is untenanted priority-0 — the
pre-PR behavior, byte for byte.

Read-your-writes: a session constructed with `worker=` belongs to a
client that also pushes through that worker. Single-process, nothing is
needed — a push lands its device program under the server lock before
the lookup's gather is dispatched, and dispatch order serializes
programs on the pools. Multi-process, the session forwards the worker's
outstanding cross-process write futures as the coalesced pull's `after`
ordering (the same contract `Worker.pull` applies to its own pulls), so
a push-then-lookup client observes its push even when the pushed key's
owner is a remote process.

Deadline semantics (docs/SERVING.md "Deadlines"):
  - checked at dispatcher take time: an expired queued request is shed
    (`serve.shed_total`) with `DeadlineExceededError`;
  - checked while the client waits: on timeout the client sheds the
    request itself if no micro-batch claimed it yet;
  - a request already CLAIMED by an in-flight micro-batch completes and
    its (slightly late) values are returned — the device gather is
    already paid for and the result is correct; deadlines gate queueing
    and dispatch, not a gather in flight. A wedged dispatcher is
    fail-stopped by a bounded grace wait (`RuntimeError`), never an
    indefinite hang.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .admission import (DeadlineExceededError, LookupRequest,
                        ServeDegradedError)

# bounded grace for a CLAIMED request's in-flight delivery: a device
# gather is milliseconds; a dispatcher that cannot deliver within this
# is wedged and the lookup fail-stops instead of hanging
_CLAIMED_GRACE_S = 30.0


class ServeSession:
    """One client's handle; obtained from `ServePlane.session()`."""

    def __init__(self, plane, worker=None, tenant=None, priority=None):
        self.plane = plane
        self.server = plane.server
        self.worker = worker
        self.tenant = plane.queue.tenant(tenant) \
            if tenant is not None else None
        # explicit priority overrides the tenant's class; None defers
        # to the tenant's CURRENT priority at each lookup, so a live
        # configure_tenant() re-class reaches existing sessions
        # (untenanted default: 0, the pre-tenancy behavior)
        self._priority = None if priority is None else int(priority)

    @property
    def priority(self) -> int:
        if self._priority is not None:
            return self._priority
        return self.tenant.priority if self.tenant is not None else 0

    def lookup(self, keys, deadline_ms: Optional[float] = None,
               out: Optional[np.ndarray] = None) -> np.ndarray:
        """Coalesced, snapshot-consistent read of `keys` (any shape;
        duplicates allowed — values come back per input position).
        Returns [B, L] when the batch is uniform-length, else the flat
        per-key concat (the `Worker.pull_sync` shapes). `deadline_ms`
        defaults to `--sys.serve.deadline_ms` (0 = no deadline).

        Raises `ServeOverloadError` (queue full — backpressure),
        `DeadlineExceededError` (shed), `ServeDegradedError` (the
        server is restoring/degraded — retry once readiness recovers),
        or `RuntimeError` (plane closed / dispatcher wedged). Never
        hangs."""
        keys = np.ascontiguousarray(
            np.asarray(keys, dtype=np.int64).ravel())
        srv = self.server
        if len(keys) == 0:
            return np.empty(0, dtype=np.float32)
        # validate at the session boundary: an out-of-range key must
        # fail ITS client loudly, not poison the co-batched requests of
        # other clients inside the dispatcher
        from ..base import check_key_range
        check_key_range(keys, srv.num_keys)
        # degraded window (ISSUE 10; Server.begin_degraded — set while
        # a checkpoint-chain restore applies): shed at the door with
        # the distinct error, before the request touches the queue
        reason = srv._degraded_reason
        if reason is not None:
            self.plane.queue.c_degraded.inc()
            raise ServeDegradedError(
                f"serve degraded: {reason} — lookup shed (retry once "
                f"readiness recovers; docs/failure_handling.md)")
        lens = srv.value_lengths[keys]
        if deadline_ms is None:
            deadline_ms = self.plane.opts.serve_deadline_ms
        wt = srv.wtrace  # workload trace capture (ISSUE 15; the serve
        # half of the op stream: keys + tenant/priority/deadline)
        if wt is not None:
            wt.record_serve(
                keys,
                self.tenant.name if self.tenant is not None else None,
                self.priority, deadline_ms or 0.0)
        deadline_s = None if not deadline_ms else deadline_ms * 1e-3
        after = ()
        if self.worker is not None and srv.glob is not None:
            after = tuple(self.worker._live_write_futs())
        # request-flight tracing (--sys.trace.flight, obs/flight.py):
        # mint the per-request trace id here — the causal chain's start.
        # The id rides the queue entry, is stamped by the batcher when a
        # micro-batch claims and dispatches it, and closes below at
        # reply time; off costs exactly this one `is None` check
        fl = srv.flight
        tr = fl.mint() if fl is not None else None
        req = LookupRequest(keys, after=after, deadline_s=deadline_s,
                            trace=tr, tenant=self.tenant,
                            priority=self.priority,
                            lane=self.plane.batcher.assign_lane(keys))
        flat = self._submit_and_wait(req, deadline_s, deadline_ms,
                                     fl, tr)
        if out is not None:
            # reshape(-1) on a non-contiguous view would COPY and the
            # caller's buffer would silently stay unfilled; a too-small
            # buffer would fail with an opaque broadcast error
            if not out.flags["C_CONTIGUOUS"]:
                raise ValueError(
                    "lookup out= buffer must be C-contiguous (got a "
                    "strided view; pass np.ascontiguousarray(out))")
            if out.size < len(flat):
                raise ValueError(
                    f"lookup out= buffer too small: {out.size} < "
                    f"{len(flat)} values for this key batch")
            np.copyto(out.reshape(-1)[: len(flat)], flat)
        if len(np.unique(lens)) == 1:
            return flat.reshape(len(keys), int(lens[0]))
        return flat

    def _submit_and_wait(self, req, deadline_s, deadline_ms, fl, tr):
        """The submit/wait/shed/grace dance shared by `lookup` and
        `lookup_bags`: submit into the admission queue, wait out the
        deadline, shed if still unclaimed, bounded grace if claimed.
        Returns the delivered flat result; closes the flight trace on
        any failure so no trace dangles."""
        try:
            self.plane.queue.submit(req)  # may raise ServeOverloadError
            if not req.wait(deadline_s):
                # deadline passed while we waited: shed if still
                # unclaimed
                if req.try_shed():
                    self.plane.queue.c_shed.inc()
                    if self.tenant is not None:
                        self.tenant.c_shed.inc()
                    raise DeadlineExceededError(
                        f"lookup deadline ({deadline_ms} ms) expired "
                        f"before a micro-batch claimed the request "
                        f"(queue depth {self.plane.queue.depth()})")
                # claimed: an in-flight batch will deliver — bounded
                # grace
                if not req.wait(_CLAIMED_GRACE_S):
                    raise RuntimeError(
                        "serve dispatcher failed to deliver a claimed "
                        f"request within {_CLAIMED_GRACE_S}s — wedged "
                        "dispatcher (fail-stop, "
                        "docs/failure_handling.md)")
            flat = req.take_result()  # raises the shed/close error
        except BaseException:
            if fl is not None:
                # shed/overload/close: a terminal lookup slice records
                # the abandoned flight so no trace dangles silently
                fl.finish_lookup(tr, ok=False)
            raise
        if fl is not None:
            fl.finish_lookup(tr, ok=True)
        return flat

    def lookup_bags(self, tables, bags, pooling: str = "sum",
                    deadline_ms: Optional[float] = None):
        """Fused embedding-bag read (ISSUE 16): for each table `t`,
        `bags[t]` is a non-decreasing offsets array `[0, ..., n_t]`
        partitioning that table's member keys `tables[t]` into bags;
        the reply is one `[n_bags_t, L_t]` matrix of `pooling`-pooled
        ("sum" or "mean") vectors per table — only the POOLED vectors
        cross the device boundary on the fused path (one gather+pool
        program per length class), and every serving path returns
        bit-identical values to host-pooling `lookup` of the same
        member keys (serve/bags.py docstring; empty bags pool to
        zeros). Each table's members must share one length class —
        split mixed-length features into separate tables. Duplicated
        members accumulate per position, like an embedding bag.

        Same admission/deadline/error semantics as `lookup`."""
        if pooling not in ("sum", "mean"):
            raise ValueError("lookup_bags pooling must be 'sum' or "
                             f"'mean' (got {pooling!r})")
        if not len(tables) or len(tables) != len(bags):
            raise ValueError(
                "lookup_bags needs parallel, non-empty tables/bags "
                f"lists (got {len(tables)} tables, {len(bags)} bag "
                "offset arrays)")
        srv = self.server
        from ..base import check_key_range
        tks, tbg, lens_t = [], [], []
        for t, (ks, bg) in enumerate(zip(tables, bags)):
            ks = np.ascontiguousarray(
                np.asarray(ks, dtype=np.int64).ravel())
            bg = np.asarray(bg, dtype=np.int64).ravel()
            if len(ks) == 0:
                raise ValueError(
                    f"lookup_bags table {t}: needs >= 1 member key "
                    "(an all-empty table has no length class to pool "
                    "in)")
            if (len(bg) < 2 or bg[0] != 0 or bg[-1] != len(ks)
                    or np.any(np.diff(bg) < 0)):
                raise ValueError(
                    f"lookup_bags table {t}: bags must be "
                    "non-decreasing offsets starting at 0 and ending "
                    f"at n_members={len(ks)} (got {bg!r})")
            check_key_range(ks, srv.num_keys)
            if len(np.unique(srv.ab.key_class[ks])) != 1:
                raise ValueError(
                    f"lookup_bags table {t}: member keys span multiple "
                    "length classes — a pooled vector needs one row "
                    "width; split mixed-length features into separate "
                    "tables")
            tks.append(ks)
            tbg.append(bg)
            lens_t.append(int(srv.value_lengths[ks[0]]))
        reason = srv._degraded_reason
        if reason is not None:
            self.plane.queue.c_degraded.inc()
            raise ServeDegradedError(
                f"serve degraded: {reason} — lookup_bags shed (retry "
                f"once readiness recovers; docs/failure_handling.md)")
        allk = np.concatenate(tks) if len(tks) > 1 else tks[0]
        if deadline_ms is None:
            deadline_ms = self.plane.opts.serve_deadline_ms
        wt = srv.wtrace
        if wt is not None:
            # the serve half of the op stream sees the MEMBER keys —
            # replay reproduces the same union/access pattern
            wt.record_serve(
                allk,
                self.tenant.name if self.tenant is not None else None,
                self.priority, deadline_ms or 0.0)
        deadline_s = None if not deadline_ms else deadline_ms * 1e-3
        after = ()
        if self.worker is not None and srv.glob is not None:
            after = tuple(self.worker._live_write_futs())
        fl = srv.flight
        tr = fl.mint() if fl is not None else None
        from .bags import BagLookupRequest
        req = BagLookupRequest(
            tks, tbg, pooling, allk, after=after, deadline_s=deadline_s,
            trace=tr, tenant=self.tenant, priority=self.priority,
            lane=self.plane.batcher.assign_lane(allk))
        flat = self._submit_and_wait(req, deadline_s, deadline_ms,
                                     fl, tr)
        out, off = [], 0
        for bg, L in zip(tbg, lens_t):
            nb = len(bg) - 1
            out.append(flat[off:off + nb * L].reshape(nb, L))
            off += nb * L
        return out
