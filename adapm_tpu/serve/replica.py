"""Read-only serve replicas: the serving plane's lock-free hot-row
fast path (ISSUE 9 tentpole a).

Every r9-r13 serve lookup dispatches its union gather under the SAME
server lock that training pushes, sync rounds, and tier promotions
take — reads contend with writes on the hottest lock in the process
("Dissecting Embedding Bag Performance in DLRM Inference", PAPERS.md,
shows real DLRM serving is dominated by exactly this gather path).
This module keeps an **epoch-versioned snapshot** of the hottest rows
(GraphVite's episodic read-optimized copies, PAPERS.md, are the
structural model): a lookup whose union is fully covered by a valid
snapshot gathers from it WITHOUT the server lock; anything else falls
back to the exact locked path.

The freshness rule — what makes the lock-free read **bit-identical**
to `Worker.pull` at the same dispatch point, not merely bounded-stale:

  - the snapshot holds only **locally-owned keys with zero replicas
    anywhere** (`ab.replica_count == 0`). Replica-holding keys are
    excluded because a `--sys.sync.threshold` round merges deltas into
    owner rows ON DEVICE without a host-visible epoch bump; replica
    creation/drop/relocation all bump `topology_version`, so the
    exclusion stays sound between refreshes;
  - at refresh time (under the server lock) the per-row **write
    epochs** (`ShardedStore.export_epochs` — the r8 dirty-delta
    tracking, exported) and `topology_version` are recorded alongside
    the device gather's enqueue;
  - at serve time the lookup revalidates, lock-free: `topology_version`
    unchanged AND every covered row's `main_epoch` still equals the
    recorded export (`epochs_unchanged`). Every write path bumps the
    epoch cell under the server lock BEFORE enqueueing its program, so
    a push/set/sync/relocation/checkpoint-restore that completed
    before the lookup is always detected — **read-your-writes** holds
    for same-process clients by the epoch bump, and sessions with
    outstanding cross-process write futures skip the fast path
    entirely (the batcher falls back whenever a batch carries `after`
    ordering). Tier promotions/demotions move rows without changing
    values and deliberately do not bump: a snapshot survives them.

Any failed validation is a **fallback, never an error**: the batcher
runs the pre-PR locked path and the replica queues a coalesced
refresh on the executor's `serve_refresh` stream (throttled by
`--sys.serve.replica_refresh_ms`). The snapshot itself is produced by
a DEVICE gather over the pools (one program per length class, enqueued
under the lock, bit-exact by construction) whose output buffer is kept
device-resident and mirrored to host once per refresh — serving then
costs one numpy fancy-index per hit, zero device dispatches, zero
locks.

Row selection fuses the replica's own per-key serve-load counters
(decayed each refresh) with the tier plane's residency scores
(`TierManager.export_serve_scores`) when tiering is on — the hottest
rows by BOTH training intent and serve traffic.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import numpy as np


class _Snapshot:
    """One immutable refresh result. `keys` sorted; parallel arrays map
    each key to its length class, row in that class's value matrix, and
    the (shard, slot, epoch) triple the validation re-checks."""

    __slots__ = ("keys", "cid", "row", "o_sh", "o_sl", "epochs", "vals",
                 "dev", "topo", "t_enqueued", "version")

    def __init__(self, keys, cid, row, o_sh, o_sl, epochs, vals, dev,
                 topo, t_enqueued, version):
        self.keys = keys
        self.cid = cid
        self.row = row
        self.o_sh = o_sh
        self.o_sl = o_sl
        self.epochs = epochs
        self.vals = vals          # host mirrors, one [n, L] per class
        self.dev = dev            # the device-resident gather outputs
        self.topo = topo
        self.t_enqueued = t_enqueued
        self.version = version


class ServeReplica:
    """Owned by a ServePlane when `--sys.serve.replica_rows > 0`; the
    LookupBatcher consults it per union batch (see module docstring)."""

    def __init__(self, server, opts, registry=None):
        self.server = server
        self.rows = int(opts.serve_replica_rows)
        self.refresh_s = float(opts.serve_replica_refresh_ms) * 1e-3
        # per-key serve-load score (bumped lock-free per union batch,
        # halved each refresh — the same decayed-counter CLOCK variant
        # the tier plane uses)
        self._score = np.zeros(server.num_keys, dtype=np.int64)
        self._snap: Optional[_Snapshot] = None
        self._version = 0
        self._closed = False
        # serializes refresh bodies (the coalesced executor stream
        # already does; this guards direct refresh_now() callers too)
        self._refresh_lock = threading.Lock()
        # wall time of the last score decay: halving is TIME-based
        # (~1 Hz), never per-refresh — under load the refresh throttle
        # fires every refresh_s, and halving that often would collapse
        # every score to 0/1 and churn the selection into noise
        self._last_decay = time.monotonic()
        from ..obs.metrics import Counter
        reg = registry
        if reg is not None and reg.enabled:
            self.c_refreshes = reg.counter("serve.replica_refreshes_total",
                                           shared=True)
            self.c_stale = reg.counter(
                "serve.replica_stale_fallbacks_total", shared=True)
            reg.gauge("serve.replica_rows", shared=True,
                      fn=lambda: 0 if self._snap is None
                      else len(self._snap.keys))
        else:
            self.c_refreshes = Counter("serve.replica_refreshes_total")
            self.c_stale = Counter("serve.replica_stale_fallbacks_total")

    # -- the lock-free fast path ---------------------------------------------

    def try_serve(self, union: np.ndarray) \
            -> Optional[Tuple[np.ndarray, float]]:
        """Serve the (unique, sorted) union from the snapshot if fully
        covered and still valid; returns (flat values, the snapshot's
        under-lock enqueue stamp — the freshness probe's read-order
        cutoff) or None (caller takes the exact locked path). Bumps the
        serve-load scores either way and queues a throttled refresh on
        a miss. NEVER takes the server lock."""
        np.add.at(self._score, union, 1)
        snap = self._snap
        srv = self.server
        if snap is None or len(snap.keys) == 0:
            self.kick()
            return None
        if srv.topology_version != snap.topo:
            # placement moved (relocation / replica churn / adoption):
            # the owner-coordinate and replica-free facts are stale
            self.c_stale.inc()
            self.kick()
            return None
        pos = np.searchsorted(snap.keys, union)
        pos[pos >= len(snap.keys)] = 0
        if not np.array_equal(snap.keys[pos], union):
            self.kick()  # partial coverage: all-or-nothing fallback
            return None
        # read-your-writes / staleness guard: every covered row's main
        # epoch must still equal the snapshot-time export
        if len(srv.stores) == 1:
            if not srv.stores[0].epochs_unchanged(
                    snap.o_sh[pos], snap.o_sl[pos], snap.epochs[pos]):
                self.c_stale.inc()
                self.kick()
                return None
        else:
            cids = snap.cid[pos]
            for cid in np.unique(cids):
                m = cids == cid
                if not srv.stores[cid].epochs_unchanged(
                        snap.o_sh[pos[m]], snap.o_sl[pos[m]],
                        snap.epochs[pos[m]]):
                    self.c_stale.inc()
                    self.kick()
                    return None
        # assemble the flat union result from the host mirror (same
        # bits the locked gather would return — pinned by the storm)
        if len(srv.stores) == 1:
            flat = np.ascontiguousarray(
                snap.vals[0][snap.row[pos]]).ravel()
        else:
            from ..parallel.pm import _fill_flat, _offsets
            lens = srv.value_lengths[union]
            offs = _offsets(lens)
            flat = np.empty(offs[-1], dtype=np.float32)
            cids = snap.cid[pos]
            for cid in np.unique(cids):
                m = np.nonzero(cids == cid)[0]
                _fill_flat(flat, offs, lens, m,
                           snap.vals[cid][snap.row[pos[m]]].ravel())
        return flat, snap.t_enqueued

    # -- refresh -------------------------------------------------------------

    def kick(self) -> None:
        """Queue one coalesced refresh program on the `serve_refresh`
        stream, at most one per refresh interval (the coalesce key
        absorbs kick storms; the delay is the throttle)."""
        if self._closed:
            return
        self.server.exec.submit("serve_refresh", self._refresh,
                                label="serve.replica.refresh",
                                coalesce_key="serve.replica.refresh",
                                delay=self.refresh_s)

    def refresh_now(self) -> int:
        """Synchronous refresh (tests / the guard scripts: snapshot
        coverage without thread timing). Returns rows snapshotted."""
        self._refresh()
        snap = self._snap
        return 0 if snap is None else len(snap.keys)

    def _select(self) -> np.ndarray:
        """Top-`rows` keys by serve-load score fused with tier
        residency scores (host, lock-free). Decays the serve counters
        about once a second so the hot set tracks shifting traffic
        without collapsing under a fast refresh cadence."""
        srv = self.server
        score = self._score
        if srv.tier is not None:
            score = score + srv.tier.export_serve_scores()
        else:
            score = score.copy()
        now = time.monotonic()
        if now - self._last_decay >= 1.0:
            self._last_decay = now
            self._score >>= 1
        live = int(np.count_nonzero(score))
        k = min(self.rows, live)
        if k == 0:
            return np.empty(0, dtype=np.int64)
        cand = np.argpartition(score, -k)[-k:]
        cand = cand[score[cand] > 0]
        cand.sort()
        return cand.astype(np.int64)

    def _refresh(self) -> None:
        """One snapshot rebuild: select candidates, then under the
        server lock filter to owned replica-free keys, record epochs +
        topology_version, and enqueue one device gather per length
        class; materialize the host mirror outside the lock and swap
        the snapshot reference atomically."""
        if self._closed:
            return
        with self._refresh_lock:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        from ..core.store import OOB
        srv = self.server
        cand = self._select()
        if len(cand) == 0:
            return
        per_class: List = []
        with srv._lock:
            ab = srv.ab
            # replica-free, locally-owned keys only (module docstring:
            # thresholded syncs merge into replica-holding owner rows
            # without an epoch bump; replica churn bumps
            # topology_version, keeping this filter sound between
            # refreshes)
            ok = (ab.owner[cand] >= 0) & (ab.replica_count[cand] == 0)
            keys = cand[ok]
            if len(keys) == 0:
                return
            topo = srv.topology_version
            kcid = np.zeros(len(keys), dtype=np.int32)
            krow = np.zeros(len(keys), dtype=np.int32)
            o_sh = np.zeros(len(keys), dtype=np.int32)
            o_sl = np.zeros(len(keys), dtype=np.int32)
            epochs = np.zeros(len(keys), dtype=np.int64)
            for cid, pos in srv._group_by_class(keys):
                ks = keys[pos]
                st = srv.stores[cid]
                sh = ab.owner[ks].astype(np.int32)
                sl = ab.slot[ks].astype(np.int32)
                kcid[pos] = cid
                krow[pos] = np.arange(len(ks), dtype=np.int32)
                o_sh[pos], o_sl[pos] = sh, sl
                # epochs recorded BEFORE the gather enqueue, both under
                # the lock: any write enqueued earlier has already
                # bumped its cell (and the gather reads its value); any
                # later write bumps after, failing validation
                epochs[pos] = st.export_epochs(sh, sl)
                n = len(ks)
                dev = st.gather(sh, sl, np.zeros(n, np.int32),
                                np.full(n, OOB, np.int32),
                                np.zeros(n, bool))
                per_class.append((cid, pos, dev, n))
            t_enqueued = time.perf_counter()
        # device -> host mirror outside the lock (the gather output is
        # a fresh, never-donated buffer; blocking here stalls only the
        # refresh stream, never a client)
        nclasses = len(srv.stores)
        vals: List = [None] * nclasses
        devs: List = [None] * nclasses
        for cid, pos, dev, n in per_class:
            vals[cid] = np.asarray(dev)[:n]
            devs[cid] = dev
        self._version += 1
        self._snap = _Snapshot(keys, kcid, krow, o_sh, o_sl, epochs,
                               vals, devs, topo, t_enqueued,
                               self._version)
        self.c_refreshes.inc()

    # -- lifecycle -----------------------------------------------------------

    @property
    def version(self) -> int:
        return self._version

    def close(self) -> None:
        """Stop refreshing and drain the `serve_refresh` stream (a
        queued refresh sees `_closed` and exits; a RUNNING one reads
        through the pools, so teardown must wait for it). Idempotent."""
        self._closed = True
        ex = self.server.exec
        if not ex.closed and not ex.drain("serve_refresh", timeout=30):
            from ..utils import alog
            alog("[serve] replica refresh failed to drain within 30s — "
                 "wedged mid-gather")
            raise RuntimeError(
                "serve replica refresh wedged: did not drain within "
                "30s of close; refusing to proceed into pool teardown "
                "under a live reader")
        self._snap = None
