"""Admission control for the online serving plane: bounded queue,
deadlines, shed-don't-hang.

The serve plane follows the fail-stop stance of docs/failure_handling.md:
an overloaded or expired request is rejected LOUDLY — `submit` raises
`ServeOverloadError` the instant the bounded queue is full (backpressure
the caller can act on: retry, spill, or scale), and a request whose
deadline passes before a micro-batch claims it is shed with
`DeadlineExceededError`. Nothing is ever parked indefinitely: the
dispatcher checks deadlines at take time, the client checks them while
waiting, and the two sides arbitrate through a tiny claim/shed state
machine so a request is served exactly once or shed exactly once, never
both and never neither.

Request lifecycle:

    PENDING --try_claim()--> CLAIMED --deliver()/fail()--> done
       \\--try_shed()--> SHED (fail(DeadlineExceededError))

`try_claim` (dispatcher) and `try_shed` (client timeout, or the
dispatcher's take-time expiry sweep) race under the request's lock;
whoever flips the state first wins. A CLAIMED request is part of an
in-flight micro-batch and will be delivered (the device gather is
already paid for); a SHED request's eventual result, if any, is
discarded by the dispatcher's claim failure.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np


class ServeOverloadError(RuntimeError):
    """The bounded admission queue is full — backpressure, not a bug.

    Raised synchronously by `AdmissionQueue.submit`; the caller decides
    whether to retry, drop, or surface the overload. Counted in
    `serve.rejected_total`."""


class DeadlineExceededError(TimeoutError):
    """A lookup's deadline passed before it was served. Counted in
    `serve.shed_total`."""


_PENDING, _CLAIMED, _SHED = 0, 1, 2


class LookupRequest:
    """One client lookup: the key batch, optional read-your-writes
    ordering futures, a deadline, and the delivery rendezvous."""

    __slots__ = ("keys", "after", "deadline", "t0", "result", "error",
                 "trace", "_state", "_lock", "_done")

    def __init__(self, keys: np.ndarray, after: Sequence = (),
                 deadline_s: Optional[float] = None, trace=None):
        self.keys = keys
        # request-flight trace context (obs/flight.py FlightTrace),
        # minted by the session when --sys.trace.flight is on; None —
        # the common case — costs nothing anywhere below
        self.trace = trace
        # outstanding cross-process write futures of the client's worker:
        # the coalesced pull is ordered after them, so a client that also
        # pushes reads its own writes (same `after` contract as
        # Worker.pull; single-process ordering needs nothing — a push
        # lands under the server lock before the lookup's gather is
        # dispatched)
        self.after: Tuple = tuple(after)
        self.deadline = None if deadline_s is None \
            else time.monotonic() + deadline_s
        self.t0 = time.perf_counter()   # serve.latency_s start
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self._state = _PENDING
        self._lock = threading.Lock()
        self._done = threading.Event()

    # -- state machine -------------------------------------------------------

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (time.monotonic() if now is None else now) >= self.deadline

    def try_claim(self) -> bool:
        """Dispatcher side: move PENDING -> CLAIMED."""
        with self._lock:
            if self._state != _PENDING:
                return False
            self._state = _CLAIMED
            if self.trace is not None:
                # end of queue residence: the flight's queue_s segment
                # closes here, batch_wait_s starts
                self.trace.t_claim = time.perf_counter()
            return True

    def try_shed(self) -> bool:
        """Shed side (client timeout / take-time expiry sweep): move
        PENDING -> SHED. False means a micro-batch already claimed it."""
        with self._lock:
            if self._state != _PENDING:
                return False
            self._state = _SHED
            return True

    @property
    def claimed(self) -> bool:
        return self._state == _CLAIMED

    # -- delivery ------------------------------------------------------------

    def deliver(self, flat: np.ndarray) -> None:
        self.result = flat
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self.error = exc
        self._done.set()

    def wait(self, timeout: Optional[float]) -> bool:
        return self._done.wait(timeout)

    def take_result(self) -> np.ndarray:
        if self.error is not None:
            raise self.error
        return self.result


class AdmissionQueue:
    """Bounded FIFO of LookupRequests with dispatcher-side micro-batch
    take. `submit` never blocks: a full queue raises ServeOverloadError
    immediately (the backpressure contract). `take` blocks until at least
    one live request exists, then lingers up to `max_wait_s` to coalesce
    more — the micro-batch window.

    Metrics (registered in the server's registry, `shared=True` so a
    plane torn down and rebuilt on the same server reuses them):
    `serve.queue_depth` gauge, `serve.rejected_total` /
    `serve.shed_total` counters."""

    def __init__(self, bound: int, registry=None):
        assert bound >= 1, "admission queue bound must be >= 1"
        self.bound = int(bound)
        self._q: "collections.deque[LookupRequest]" = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        # dispatcher kick (PR 6): the LookupBatcher registers a callback
        # that queues a drain program on the executor's `serve` stream —
        # event-driven dispatch instead of a thread parked in take()
        self._kick = None
        from ..obs.metrics import Counter
        if registry is not None and registry.enabled:
            self.c_rejected = registry.counter("serve.rejected_total",
                                               shared=True)
            self.c_shed = registry.counter("serve.shed_total", shared=True)
            registry.gauge("serve.queue_depth", fn=self.depth,
                           shared=True)
        else:
            # standalone counters: shed/reject accounting survives
            # --sys.metrics 0 (the session reads c_shed for its own
            # bookkeeping either way)
            self.c_rejected = Counter("serve.rejected_total")
            self.c_shed = Counter("serve.shed_total")

    def depth(self) -> int:
        """LIVE (still-pending) requests queued — the number that counts
        against the bound. Client-shed corpses linger in the deque until
        a take or an at-bound submit compacts them; counting them here
        would let readiness report a saturated queue that the very next
        submit would admit into. Under the lock — iterating the deque
        while the dispatcher poplefts would raise 'deque mutated during
        iteration'. O(queue bound), probe-frequency only."""
        with self._cond:
            return sum(1 for r in self._q if r._state == _PENDING)

    # -- producer (client sessions) ------------------------------------------

    def submit(self, req: LookupRequest) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("serve plane is closed")
            if len(self._q) >= self.bound:
                # client-shed requests linger in the deque until a take
                # skips them; they must not count against the bound
                # (only LIVE requests are backpressure), so compact
                # before rejecting
                self._q = collections.deque(
                    r for r in self._q if r._state == _PENDING)
            if len(self._q) >= self.bound:
                self.c_rejected.inc()
                raise ServeOverloadError(
                    f"serve admission queue full ({self.bound} pending): "
                    f"backpressure — retry later, shed load, or raise "
                    f"--sys.serve.queue")
            self._q.append(req)
            self._cond.notify()
            kick = self._kick
        if kick is not None:
            # outside the queue lock: the kick enqueues an executor
            # program (leaf lock), and a submit that loses the race with
            # a running drain still queues the NEXT drain — no lost
            # wakeup (the drain re-checks the queue before exiting
            # either way, but the invariant is: every admitted request
            # has a drain program submitted after it)
            kick()

    def set_kick(self, fn) -> None:
        """Register (or clear, fn=None) the dispatcher kick called after
        every successful submit (PR 6 executor-driven dispatch)."""
        with self._cond:
            self._kick = fn

    # -- consumer (the LookupBatcher drain program) --------------------------

    def _pop_live_locked(self) -> Optional[LookupRequest]:
        """Next claimable request; sheds expired ones on the way (the
        take-time deadline check). Caller holds the condition lock."""
        while self._q:
            r = self._q.popleft()
            if r.expired():
                if r.try_shed():
                    self.c_shed.inc()
                    r.fail(DeadlineExceededError(
                        "lookup deadline expired before dispatch "
                        "(queue wait exceeded deadline_ms)"))
                continue
            if r.try_claim():
                return r
            # client shed it while queued: already failed, skip
        return None

    def take(self, max_batch: int, max_wait_s: float,
             block: bool = True):
        """Claim up to `max_batch` live requests: wait for the first
        (`block=False` — the executor-driven drain — returns []
        immediately instead, since a kick already guarantees a follow-up
        drain for any later submit), then linger up to `max_wait_s` to
        coalesce more (the micro-batch window). Returns [] when there is
        nothing to claim (closed queue, or empty with block=False)."""
        with self._cond:
            while True:
                first = self._pop_live_locked()
                if first is not None:
                    break
                if self._closed or not block:
                    return []
                self._cond.wait()
            out = [first]
            if max_wait_s > 0 and len(out) < max_batch:
                limit = time.monotonic() + max_wait_s
                while len(out) < max_batch and not self._closed:
                    nxt = self._pop_live_locked()
                    if nxt is not None:
                        out.append(nxt)
                        continue
                    rem = limit - time.monotonic()
                    if rem <= 0:
                        break
                    self._cond.wait(rem)
            else:
                # zero-wait window: drain whatever is already queued
                while len(out) < max_batch:
                    nxt = self._pop_live_locked()
                    if nxt is None:
                        break
                    out.append(nxt)
            return out

    def close(self) -> None:
        """Stop admitting, wake the dispatcher, and fail-stop every
        still-pending request (never leave a waiter hanging)."""
        with self._cond:
            self._closed = True
            pending = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in pending:
            if r.try_shed():
                r.fail(RuntimeError("serve plane closed while the "
                                    "request was queued"))
