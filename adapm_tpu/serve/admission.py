"""Admission control for the online serving plane: bounded lanes,
deadlines, tenant quotas, priority classes — shed-don't-hang.

The serve plane follows the fail-stop stance of docs/failure_handling.md:
an overloaded or expired request is rejected LOUDLY — `submit` raises
`ServeOverloadError` the instant the bounded queue is full (backpressure
the caller can act on: retry, spill, or scale), and a request whose
deadline passes before a micro-batch claims it is shed with
`DeadlineExceededError`. Nothing is ever parked indefinitely: the
dispatcher checks deadlines at take time, the client checks them while
waiting, and the two sides arbitrate through a tiny claim/shed state
machine so a request is served exactly once or shed exactly once, never
both and never neither.

Request lifecycle:

    PENDING --try_claim()--> CLAIMED --deliver()/fail()--> done
       \\--try_shed()--> SHED (fail(DeadlineExceededError))

`try_claim` (a dispatcher) and `try_shed` (client timeout, the take-time
expiry sweep, or a priority preemption) race under the request's lock;
whoever flips the state first wins. A CLAIMED request is part of an
in-flight micro-batch and will be delivered (the device gather is
already paid for); a SHED request's eventual result, if any, is
discarded by the dispatcher's claim failure. The state machine is
**N-consumer safe**: any number of concurrent `take` callers claim
disjoint request sets (each transition commits under the request lock
inside the queue's condition lock), which is what lets ISSUE 9's
sharded dispatchers drain one queue.

ISSUE 9 additions, all inert until configured:

  - **Lanes** (`lanes=N`, wired to `--sys.serve.dispatchers`): N
    internal FIFOs sharing ONE bound, each drained by its own
    dispatcher stream so a long-row length class cannot head-of-line-
    block short ones. `lanes=1` is byte-for-byte the pre-PR queue.
  - **Tenants** (`configure_tenant`): per-tenant token-bucket quotas
    (reject at submit when the bucket is dry — quota backpressure, not
    global overload) and priority classes. Per-tenant served / shed /
    rejected counters land in the `serve.tenant.<name>.*` namespace
    (schema v8).
  - **Priority-aware pressure**: at a full queue, a submission may
    PREEMPT a strictly-lower-priority pending request (shed it loudly,
    admit the newcomer) — under pressure the low-priority class sheds
    first instead of the high-priority class rejecting. Batch
    formation fair-shares the budget: highest priority first, then
    round-robin across tenants within a priority class, then FIFO —
    no FIFO starvation of a light tenant behind a flooding one. With
    no tenants configured and all-default priorities the take path is
    the exact pre-PR FIFO.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class ServeOverloadError(RuntimeError):
    """The bounded admission queue (or a tenant's quota bucket) is
    full/dry — backpressure, not a bug.

    Raised synchronously by `AdmissionQueue.submit`, and delivered to a
    pending low-priority request preempted by a higher-priority
    submission under pressure; the caller decides whether to retry,
    drop, or surface the overload. Counted in `serve.rejected_total`
    (submit-side) / `serve.shed_total` (preemption-side)."""


class DeadlineExceededError(TimeoutError):
    """A lookup's deadline passed before it was served. Counted in
    `serve.shed_total`."""


class ServeDegradedError(RuntimeError):
    """The server is DEGRADED (a checkpoint-chain restore is applying —
    fault/ckpt.py restore_chain — or an operator opened a maintenance
    window with `Server.begin_degraded`): lookups are shed loudly with
    this distinct error instead of risking a read that mixes pre- and
    post-restore bits. Checked at session submit (fast rejection at
    the door) AND at dispatcher batch-serve time (requests already
    queued when the window opened). Counted in
    `serve.degraded_shed_total`; the bit-identity contract holds —
    a degraded server never returns a torn or stale value, it returns
    THIS error (docs/failure_handling.md)."""


_PENDING, _CLAIMED, _SHED = 0, 1, 2


class TenantState:
    """One tenant's admission policy + accounting: a token bucket
    (qps/burst; qps=0 = unthrottled) and a priority class. Owned by the
    AdmissionQueue; sessions bind to it by name."""

    __slots__ = ("name", "priority", "rate", "burst", "_tokens",
                 "_t_last", "_lock", "c_served", "c_shed", "c_rejected")

    def __init__(self, name: str, priority: int = 0, qps: float = 0.0,
                 burst: Optional[float] = None, registry=None):
        self.name = name
        self.priority = int(priority)
        self.rate = float(qps)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        self._tokens = self.burst
        self._t_last = time.monotonic()
        self._lock = threading.Lock()
        from ..obs.metrics import Counter
        if registry is not None and registry.enabled:
            def mk(leaf):
                return registry.counter(f"serve.tenant.{name}.{leaf}",
                                        shared=True)
        else:
            def mk(leaf):
                return Counter(f"serve.tenant.{name}.{leaf}")
        self.c_served = mk("served_total")
        self.c_shed = mk("shed_total")
        self.c_rejected = mk("rejected_total")

    def configure(self, priority: int = 0, qps: float = 0.0,
                  burst: Optional[float] = None) -> None:
        with self._lock:
            self.priority = int(priority)
            self.rate = float(qps)
            self.burst = float(burst) if burst is not None \
                else max(1.0, self.rate)
            self._tokens = min(self._tokens, self.burst)

    def try_admit(self) -> bool:
        """Consume one quota token; True when admitted (qps=0 always
        admits). Standard lazily-refilled token bucket."""
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst,
                self._tokens + (now - self._t_last) * self.rate)
            self._t_last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def refund(self) -> None:
        """Return a consumed token (a submit that passed the bucket but
        was then rejected at the queue bound must not burn quota — the
        tenant was never served; without the refund a saturated queue
        double-punishes it with overload AND a drained bucket)."""
        if self.rate <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, self._tokens + 1.0)


class LookupRequest:
    """One client lookup: the key batch, optional read-your-writes
    ordering futures, a deadline, tenancy, and the delivery
    rendezvous."""

    __slots__ = ("keys", "after", "deadline", "t0", "result", "error",
                 "trace", "tenant", "priority", "lane", "_state",
                 "_lock", "_done")

    def __init__(self, keys: np.ndarray, after: Sequence = (),
                 deadline_s: Optional[float] = None, trace=None,
                 tenant: Optional[TenantState] = None,
                 priority: int = 0, lane: int = 0):
        self.keys = keys
        # request-flight trace context (obs/flight.py FlightTrace),
        # minted by the session when --sys.trace.flight is on; None —
        # the common case — costs nothing anywhere below
        self.trace = trace
        # outstanding cross-process write futures of the client's worker:
        # the coalesced pull is ordered after them, so a client that also
        # pushes reads its own writes (same `after` contract as
        # Worker.pull; single-process ordering needs nothing — a push
        # lands under the server lock before the lookup's gather is
        # dispatched)
        self.after: Tuple = tuple(after)
        self.deadline = None if deadline_s is None \
            else time.monotonic() + deadline_s
        self.t0 = time.perf_counter()   # serve.latency_s start
        self.tenant = tenant
        self.priority = int(priority)
        self.lane = int(lane)
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self._state = _PENDING
        self._lock = threading.Lock()
        self._done = threading.Event()

    # -- state machine -------------------------------------------------------

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (time.monotonic() if now is None else now) >= self.deadline

    def try_claim(self) -> bool:
        """Dispatcher side: move PENDING -> CLAIMED."""
        with self._lock:
            if self._state != _PENDING:
                return False
            self._state = _CLAIMED
            if self.trace is not None:
                # end of queue residence: the flight's queue_s segment
                # closes here, batch_wait_s starts
                self.trace.t_claim = time.perf_counter()
            return True

    def try_shed(self) -> bool:
        """Shed side (client timeout / take-time expiry sweep /
        priority preemption): move PENDING -> SHED. False means a
        micro-batch already claimed it."""
        with self._lock:
            if self._state != _PENDING:
                return False
            self._state = _SHED
            return True

    @property
    def claimed(self) -> bool:
        return self._state == _CLAIMED

    # -- delivery ------------------------------------------------------------

    def deliver(self, flat: np.ndarray) -> None:
        self.result = flat
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self.error = exc
        self._done.set()

    def wait(self, timeout: Optional[float]) -> bool:
        return self._done.wait(timeout)

    def take_result(self) -> np.ndarray:
        if self.error is not None:
            raise self.error
        return self.result


class AdmissionQueue:
    """Bounded lanes of LookupRequests with dispatcher-side micro-batch
    take (see module docstring). `submit` never blocks: a full queue
    raises ServeOverloadError immediately — after attempting a
    priority preemption when the submission outranks a pending request
    — and a dry tenant bucket rejects before touching the bound.
    `take(lane=i)` blocks until at least one live request exists in
    lane i, then lingers up to `max_wait_s` to coalesce more — the
    micro-batch window.

    Metrics (registered in the server's registry, `shared=True` so a
    plane torn down and rebuilt on the same server reuses them):
    `serve.queue_depth` gauge, per-lane `serve.lane_depth.<i>` gauges,
    `serve.rejected_total` / `serve.shed_total` counters, and the
    per-tenant `serve.tenant.<name>.*` counters."""

    def __init__(self, bound: int, registry=None, lanes: int = 1,
                 lockorder: bool = False):
        assert bound >= 1, "admission queue bound must be >= 1"
        self.bound = int(bound)
        self.lanes = max(1, int(lanes))
        self._lanes: List["collections.deque[LookupRequest]"] = [
            collections.deque() for _ in range(self.lanes)]
        if lockorder:
            # runtime lock-order sentinel (--sys.lint.lockorder;
            # lint/lockorder.py): the admission condvar's lock joins
            # the process-wide acquisition graph — off, a plain
            # Condition, zero wrapper cost
            from ..lint.lockorder import SentinelLock
            self._cond = threading.Condition(
                SentinelLock("serve_admission"))
        else:
            self._cond = threading.Condition()
        self._closed = False
        self._registry = registry
        self._tenants: Dict[str, TenantState] = {}
        # QoS selection engages only once a tenant exists or a
        # non-default priority has been submitted; before that the take
        # path is the exact pre-PR FIFO (the r13-parity pin)
        self._has_qos = False
        # dispatcher kick (PR 6): the LookupBatcher registers a callback
        # that queues a drain program on the lane's executor stream —
        # event-driven dispatch instead of a thread parked in take()
        self._kick = None
        from ..obs.metrics import Counter
        if registry is not None and registry.enabled:
            self.c_rejected = registry.counter("serve.rejected_total",
                                               shared=True)
            self.c_shed = registry.counter("serve.shed_total", shared=True)
            self.c_degraded = registry.counter(
                "serve.degraded_shed_total", shared=True)
            registry.gauge("serve.queue_depth", fn=self.depth,
                           shared=True)
            for i in range(self.lanes):
                registry.gauge(f"serve.lane_depth.{i}", shared=True,
                               fn=lambda i=i: self.lane_depth(i))
        else:
            # standalone counters: shed/reject accounting survives
            # --sys.metrics 0 (the session reads c_shed for its own
            # bookkeeping either way)
            self.c_rejected = Counter("serve.rejected_total")
            self.c_shed = Counter("serve.shed_total")
            self.c_degraded = Counter("serve.degraded_shed_total")

    # -- tenancy -------------------------------------------------------------

    def configure_tenant(self, name: str, priority: int = 0,
                         qps: float = 0.0,
                         burst: Optional[float] = None) -> TenantState:
        """Create or update a tenant's admission policy. Tenant names
        must be metric-name safe (no dots/spaces — they become the
        `serve.tenant.<name>.*` namespace)."""
        if not name or any(c in name for c in ". \t\n"):
            raise ValueError(
                f"tenant name {name!r} must be non-empty and contain "
                f"no dots or whitespace (it names the "
                f"serve.tenant.<name>.* metrics)")
        with self._cond:
            ts = self._tenants.get(name)
            if ts is None:
                ts = self._tenants[name] = TenantState(
                    name, priority=priority, qps=qps, burst=burst,
                    registry=self._registry)
            else:
                ts.configure(priority=priority, qps=qps, burst=burst)
            self._has_qos = True
            return ts

    def tenant(self, name: str) -> TenantState:
        """The tenant's state, auto-created unthrottled at priority 0
        when never configured (sessions may name tenants first; the
        operator's configure_tenant tightens policy later)."""
        with self._cond:
            ts = self._tenants.get(name)
            if ts is None:
                ts = self._tenants[name] = TenantState(
                    name, registry=self._registry)
                self._has_qos = True
            return ts

    def tenants(self) -> Dict[str, TenantState]:
        with self._cond:
            return dict(self._tenants)

    # -- depth accounting ----------------------------------------------------

    def depth(self) -> int:
        """LIVE (still-pending) requests queued across all lanes — the
        number that counts against the bound. Client-shed corpses
        linger in the deques until a take or an at-bound submit
        compacts them; counting them here would let readiness report a
        saturated queue that the very next submit would admit into.
        Under the lock — iterating a deque while a dispatcher poplefts
        would raise 'deque mutated during iteration'. O(queue bound),
        probe-frequency only."""
        with self._cond:
            return sum(1 for dq in self._lanes for r in dq
                       if r._state == _PENDING)

    def lane_depth(self, lane: int) -> int:
        """Live requests pending in one lane (the per-dispatcher depth
        gauge, schema v8)."""
        if not (0 <= lane < self.lanes):
            return 0
        with self._cond:
            return sum(1 for r in self._lanes[lane]
                       if r._state == _PENDING)

    def _compact_locked(self) -> None:
        """Drop non-pending corpses from every lane (caller holds
        _cond). Exact: a request is removed only once it can never be
        claimed again, so bound accounting never double-counts and
        never loses a live request — pinned by the compaction-race
        test."""
        for i, dq in enumerate(self._lanes):
            if any(r._state != _PENDING for r in dq):
                self._lanes[i] = collections.deque(
                    r for r in dq if r._state == _PENDING)

    # -- producer (client sessions) ------------------------------------------

    def submit(self, req: LookupRequest) -> None:
        lane = req.lane % self.lanes
        req.lane = lane
        with self._cond:
            if self._closed:
                raise RuntimeError("serve plane is closed")
            if req.priority != 0:
                self._has_qos = True
            tn = req.tenant
            if tn is not None and not tn.try_admit():
                tn.c_rejected.inc()
                self.c_rejected.inc()
                raise ServeOverloadError(
                    f"tenant {tn.name!r} quota exceeded "
                    f"({tn.rate:g} qps, burst {tn.burst:g}): "
                    f"backpressure — slow down or raise the quota")
            # O(lanes) raw-length check on the common path; the
            # O(queued) corpse scan happens only at the bound
            if sum(len(dq) for dq in self._lanes) >= self.bound:
                # client-shed requests linger in the deques until a take
                # skips them; they must not count against the bound
                # (only LIVE requests are backpressure), so compact
                # before rejecting (post-compaction, raw length == live
                # count — every surviving entry was PENDING)
                self._compact_locked()
            if sum(len(dq) for dq in self._lanes) >= self.bound:
                # priority preemption: under pressure the LOWEST
                # priority class sheds first — a submission that
                # strictly outranks some pending request takes its slot
                victim = self._preempt_victim_locked(req.priority)
                if victim is None:
                    if tn is not None:
                        tn.refund()  # never served: the token goes back
                        tn.c_rejected.inc()
                    self.c_rejected.inc()
                    raise ServeOverloadError(
                        f"serve admission queue full ({self.bound} "
                        f"pending): backpressure — retry later, shed "
                        f"load, or raise --sys.serve.queue")
                self.c_shed.inc()
                if victim.tenant is not None:
                    victim.tenant.c_shed.inc()
                victim.fail(ServeOverloadError(
                    f"shed under pressure: preempted by a priority-"
                    f"{req.priority} submission (this request's "
                    f"priority: {victim.priority})"))
                self._compact_locked()
            self._lanes[lane].append(req)
            self._cond.notify_all()
            kick = self._kick
        if kick is not None:
            # outside the queue lock: the kick enqueues an executor
            # program (leaf lock), and a submit that loses the race with
            # a running drain still queues the NEXT drain — no lost
            # wakeup (the drain re-checks the queue before exiting
            # either way, but the invariant is: every admitted request
            # has a drain program submitted after it)
            kick(lane)

    def _preempt_victim_locked(self, priority: int) \
            -> Optional[LookupRequest]:
        """Shed candidate for an at-bound submission: the most recently
        queued PENDING request of the lowest priority class strictly
        below `priority` (newest-first within the class — it has waited
        least). Returns the request already moved to SHED, or None.
        Caller holds _cond and fails/compacts the victim."""
        best = None
        for dq in self._lanes:
            for r in reversed(dq):
                if r._state != _PENDING or r.priority >= priority:
                    continue
                if best is None or r.priority < best.priority:
                    best = r
        if best is not None and best.try_shed():
            return best
        return None

    def set_kick(self, fn) -> None:
        """Register (or clear, fn=None) the dispatcher kick called with
        the admitted request's lane after every successful submit
        (PR 6 executor-driven dispatch; ISSUE 9: per-lane streams)."""
        with self._cond:
            self._kick = fn

    # -- consumer (the LookupBatcher drain programs) -------------------------

    def _pop_live_locked(self, dq) -> Optional[LookupRequest]:
        """Next claimable request from `dq` in FIFO order; sheds
        expired ones on the way (the take-time deadline check). Caller
        holds the condition lock."""
        while dq:
            r = dq.popleft()
            if r.expired():
                if r.try_shed():
                    self.c_shed.inc()
                    if r.tenant is not None:
                        r.tenant.c_shed.inc()
                    r.fail(DeadlineExceededError(
                        "lookup deadline expired before dispatch "
                        "(queue wait exceeded deadline_ms)"))
                continue
            if r.try_claim():
                return r
            # client shed it while queued: already failed, skip
        return None

    def _claim_next_locked(self, dq, taken,
                           prio: Optional[int] = None) \
            -> Optional[LookupRequest]:
        """One claim for the forming micro-batch. FIFO when no QoS
        state exists (the exact pre-PR path); otherwise fair-share
        selection: highest priority first, then the tenant with the
        fewest requests already in THIS batch (`taken` counts them;
        round-robin across tenants within a priority class), then
        FIFO. `prio` (set after a batch's first claim) keeps batches
        PRIORITY-PURE: a high-priority batch never unions low-priority
        keys into its gather, so the low class cannot drag the high
        class's tail through the locked path — the latency-isolation
        half of the QoS contract (the next drain iteration serves the
        lower class). Caller holds _cond."""
        if not self._has_qos:
            return self._pop_live_locked(dq)
        now = time.monotonic()
        best = None
        for r in dq:
            if r._state != _PENDING:
                continue
            if r.expired(now):
                if r.try_shed():
                    self.c_shed.inc()
                    if r.tenant is not None:
                        r.tenant.c_shed.inc()
                    r.fail(DeadlineExceededError(
                        "lookup deadline expired before dispatch "
                        "(queue wait exceeded deadline_ms)"))
                continue
            if prio is not None and r.priority != prio:
                continue
            if best is None:
                best = r
                continue
            if r.priority != best.priority:
                if r.priority > best.priority:
                    best = r
                continue
            # same priority: fair-share — fewer batch slots used by
            # this request's tenant wins; FIFO breaks the tie (deque
            # iteration order is arrival order, so `best` is earlier)
            rt = r.tenant.name if r.tenant is not None else ""
            bt = best.tenant.name if best.tenant is not None else ""
            if taken.get(rt, 0) < taken.get(bt, 0):
                best = r
        if best is not None and best.try_claim():
            tname = best.tenant.name if best.tenant is not None else ""
            taken[tname] = taken.get(tname, 0) + 1
            # leave the claimed corpse in place; the periodic
            # compaction (and FIFO popleft skip) removes it
            return best
        if best is not None:
            # lost the race to a concurrent shed — rescan
            return self._claim_next_locked(dq, taken, prio=prio)
        return None

    def take(self, max_batch: int, max_wait_s: float,
             block: bool = True, lane: int = 0,
             wait_s_by_prio: Optional[Dict[int, float]] = None):
        """Claim up to `max_batch` live requests from `lane`: wait for
        the first (`block=False` — the executor-driven drain — returns
        [] immediately instead, since a kick already guarantees a
        follow-up drain for any later submit), then linger up to
        `max_wait_s` to coalesce more (the micro-batch window). Safe
        for N concurrent callers (disjoint claims by the state
        machine). Returns [] when there is nothing to claim (closed
        queue, or empty with block=False).

        `wait_s_by_prio` (ISSUE 20 satellite; per-class SLO targets)
        overrides the linger window per priority CLASS: batches are
        priority-pure (the `prio` pin below), so once the first claim
        fixes the batch's class, that class's window — walked
        independently by the SLO controller — replaces `max_wait_s`.
        Classes without an override keep the base window; None (the
        default, and the only value without `--sys.serve.slo_ms`
        class overrides) leaves this path byte-identical."""
        dq = self._lanes[lane % self.lanes]
        taken: Dict[str, int] = {}
        with self._cond:
            while True:
                first = self._claim_next_locked(dq, taken)
                if first is not None:
                    break
                if self._closed or not block:
                    return []
                self._cond.wait()
            out = [first]
            prio = first.priority if self._has_qos else None
            if wait_s_by_prio is not None and prio is not None:
                max_wait_s = wait_s_by_prio.get(prio, max_wait_s)
            if max_wait_s > 0 and len(out) < max_batch:
                limit = time.monotonic() + max_wait_s
                while len(out) < max_batch and not self._closed:
                    nxt = self._claim_next_locked(dq, taken, prio=prio)
                    if nxt is not None:
                        out.append(nxt)
                        continue
                    rem = limit - time.monotonic()
                    if rem <= 0:
                        break
                    self._cond.wait(rem)
            else:
                # zero-wait window: drain whatever is already queued
                while len(out) < max_batch:
                    nxt = self._claim_next_locked(dq, taken, prio=prio)
                    if nxt is None:
                        break
                    out.append(nxt)
            if self._has_qos:
                # QoS claims leave corpses in place; compact so the
                # bound reflects live work only
                self._compact_locked()
            return out

    def close(self) -> None:
        """Stop admitting, wake the dispatchers, and fail-stop every
        still-pending request (never leave a waiter hanging)."""
        with self._cond:
            self._closed = True
            pending = [r for dq in self._lanes for r in dq]
            for dq in self._lanes:
                dq.clear()
            self._cond.notify_all()
        for r in pending:
            if r.try_shed():
                r.fail(RuntimeError("serve plane closed while the "
                                    "request was queued"))
