"""Liveness/readiness surface for the serving plane.

Per docs/failure_handling.md, heartbeats (`--sys.heartbeat`) and
`Server.dead_nodes()` were DETECTION-ONLY through r18: a stale peer is
reported, not replaced. With a NetPort membership plane attached
(ISSUE 19; adapm_tpu/net), detection becomes ACTION: the membership
plane promotes the dead peer's locally-replicated keys to mains
(GlobalPM.failover_dead_peer) and `dead_nodes()` reports through it, so
readiness here reflects post-failover truth — a peer stays in the
stale list only while its keys are actually unreachable, and the
embedded `failover` detail (see readiness()) records what the plane
did about it. On legacy DCN servers the contract is unchanged:
detection-only, a process with stale peers (its lookups may observe
arbitrarily stale replicas of remotely-owned keys, and cross-process
pulls may block on a dead owner) reports not-ready while continuing to
serve in-flight and local traffic; nothing hangs.

Readiness folds four signals:
  - the dispatch plane is running (a dead dispatcher serves nothing);
  - no individual dispatcher of the N sharded drains is WEDGED — busy
    on one micro-batch for longer than the wedge bound (the same 30 s
    fail-stop bound `LookupBatcher.stop` uses). One stuck dispatcher
    of N flips readiness even while the healthy ones keep serving: the
    probe reads per-drain busy stamps lock-free, so it can never hang
    behind the wedged drain it is reporting (ISSUE 9 satellite);
  - the admission queue is not saturated (depth < bound — a full queue
    is rejecting new work);
  - no peer's heartbeat has gone stale (`Server.dead_nodes`; empty when
    heartbeats are off or single-process, matching the reference's
    opt-in contract).

The `serve.ready` (0/1) and `serve.dead_peers` gauges land in
`Server.metrics_snapshot()["serve"]` (schema_version 3), and
`metrics_snapshot` additionally embeds the full `readiness()` dict when
a plane is attached, so one snapshot answers "can this process take
traffic and why not".
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional


class HealthMonitor:
    """Owned by a ServePlane; see module docstring."""

    def __init__(self, plane, max_age_s: float = 10.0,
                 dead_nodes_fn: Optional[Callable[[], list]] = None,
                 wedge_s: float = 30.0):
        self.plane = plane
        self.server = plane.server
        self.max_age_s = max_age_s
        # per-dispatcher wedge bound: a drain busy on ONE batch longer
        # than this is stuck (matches the stop()-time fail-stop bound;
        # injectable for tests)
        self.wedge_s = wedge_s
        # injectable for tests (and for deployments with an external
        # failure detector); default: the server's heartbeat-staleness
        # detection
        self._dead_nodes_fn = dead_nodes_fn or \
            (lambda: self.server.dead_nodes(self.max_age_s))
        # last readiness() result + its wall time: the gauges below read
        # this (refreshing past _GAUGE_MAX_AGE_S) instead of each paying
        # their own dead-peer probe — multi-process, one probe is a
        # coordinator KV read per peer, and one metrics_snapshot()
        # otherwise runs it once per gauge plus once for the embedded
        # readiness dict
        self._cache = None
        reg = self.server.obs
        reg.gauge("serve.ready", shared=True,
                  fn=lambda: int(self._cached()["ready"]))
        reg.gauge("serve.dead_peers", shared=True,
                  fn=lambda: len(self._cached()["dead_nodes"]))

    _GAUGE_MAX_AGE_S = 1.0

    def _cached(self) -> Dict:
        """The readiness dict for gauge reads: fresh enough, probing at
        most once per _GAUGE_MAX_AGE_S. metrics_snapshot() calls
        readiness() first, so one snapshot performs exactly one probe
        and its gauges agree with its embedded readiness dict."""
        import time
        c = self._cache
        if c is not None and time.monotonic() - c[0] < \
                self._GAUGE_MAX_AGE_S:
            return c[1]
        return self.readiness()

    def _dead(self) -> List:
        try:
            return list(self._dead_nodes_fn())
        except Exception:  # noqa: BLE001 — a failing probe is itself
            # a not-ready signal, not a crash in the metrics path
            return ["<heartbeat probe failed>"]

    def liveness(self) -> Dict:
        """Process-is-up probe: cheap, no cross-process calls."""
        return {"alive": True,
                "dispatcher_alive": self.plane.batcher.is_alive(),
                "dispatchers": self.plane.batcher.dispatchers}

    def readiness(self) -> Dict:
        """Can this process take NEW serving traffic, and if not, why.
        Always probes fresh (and refreshes the gauge cache). Never
        blocks: the wedge probe reads busy stamps, so a stuck
        dispatcher flips the signal within the wedge bound instead of
        hanging the probe behind it."""
        import time
        reasons: List[str] = []
        batcher = self.plane.batcher
        # degraded window (ISSUE 10): a restoring server sheds every
        # lookup with ServeDegradedError — not-ready by definition
        degraded = getattr(self.server, "_degraded_reason", None)
        if degraded is not None:
            reasons.append(f"degraded: {degraded} (lookups shed with "
                           f"ServeDegradedError)")
        if not batcher.is_alive():
            reasons.append("dispatcher thread not running")
        wedged = batcher.wedged_dispatchers(self.wedge_s)
        if wedged:
            reasons.append(
                f"dispatcher(s) {wedged} wedged: busy on one "
                f"micro-batch > {self.wedge_s:.0f}s (fail-stop bound, "
                f"docs/failure_handling.md)")
        depth = self.plane.queue.depth()   # live requests only
        bound = self.plane.queue.bound
        if depth >= bound:
            reasons.append(
                f"admission queue saturated ({depth}/{bound})")
        # executor watchdog (ISSUE 10): any stream whose CURRENT
        # program is busy past --sys.fault.watchdog_s is wedged — a
        # stuck sync round / tier commit / checkpoint save flips
        # readiness the same way a stuck dispatcher does (the probe
        # reads busy stamps, never blocking behind the wedged program)
        exw = self.server.exec.wedged_streams(
            self.server.opts.fault_watchdog_s,
            exclude=batcher.streams)
        if exw:
            names = [w["stream"] for w in exw]
            reasons.append(
                f"executor stream(s) {names} wedged: busy on one "
                f"program > {self.server.opts.fault_watchdog_s:.0f}s "
                f"(--sys.fault.watchdog_s)")
        dead = self._dead()
        # failover detail (ISSUE 19): when a membership plane exists,
        # report what the plane DID about the dead peers — promoted
        # replica counts, lost keys, recovery wall — next to the raw
        # detection signal (None on detection-only/legacy servers)
        net = getattr(self.server, "net", None)
        failover = None
        if net is not None:
            s = net.stats()
            failover = {"failovers": s["failovers"],
                        "failover_s": s["failover_s"],
                        "promoted_keys": s["promoted_keys"],
                        "lost_keys": s["lost_keys"],
                        "peers_live": s["peers_live"],
                        "peers_total": s["peers_total"]}
        if dead:
            if failover is not None:
                reasons.append(
                    f"dead peers {dead}: failover promoted "
                    f"{failover['promoted_keys']} replica key(s), "
                    f"{failover['lost_keys']} lost "
                    f"(docs/NETWORK.md)")
            else:
                reasons.append(
                    f"stale peer heartbeats (detection-only, "
                    f"docs/failure_handling.md): {dead}")
        out = {"ready": not reasons, "reasons": reasons,
               "dead_nodes": dead, "queue_depth": depth,
               "queue_bound": bound,
               "dispatchers": batcher.dispatchers,
               "wedged_dispatchers": wedged,
               "wedged_streams": [w["stream"] for w in exw],
               "degraded": degraded, "failover": failover}
        self._cache = (time.monotonic(), out)
        return out
