"""Micro-batching coalescer: many concurrent lookups -> one fused gather.

DLRM-style inference is dominated by the embedding lookup path, and a
dedicated request-coalescing layer in front of the parameter store is
the standard lever (GraphVite's batched sample/lookup pipeline,
PAPERS.md; "Dissecting Embedding Bag Performance in DLRM Inference").
The `LookupBatcher` dispatches as event-driven drain programs on the
unified executor (PR 6 — the dedicated dispatcher thread is subsumed by
the executor pool; every `AdmissionQueue.submit` kicks a coalesced
drain for the request's lane, and an idle plane owns no queued
program). ISSUE 9 shards the dispatch plane: `--sys.serve.dispatchers
N` runs N drains on DISTINCT executor streams (`serve`, `serve.1`,
...), one per admission lane, so a long-row length class's gather no
longer head-of-line-blocks short ones; the queue's claim/shed state
machine makes the N consumers exactly-once by construction. A drain

  1. takes up to `--sys.serve.max_batch` requests from its lane,
     lingering at most `--sys.serve.max_wait_us` after the first
     (the micro-batch window — while a batch's gather is in flight the
     queue refills, so sustained load coalesces without waiting);
  2. DEDUPLICATES the union key set (concurrent clients hit the same hot
     rows; the device gathers one row per unique key, not per request);
  3. serves the union from the READ-ONLY SERVE REPLICA when one is
     attached (`--sys.serve.replica_rows`; serve/replica.py) and its
     epoch-versioned snapshot fully covers the batch — no server lock,
     no device dispatch, bit-identical by the epoch/topology
     validation — otherwise dispatches ONE fused gather per length
     class through the exact Pull machinery the training path uses —
     the routing-plan cache, `Server._plan_pull`, and `Server._pull`
     under the server lock — and scatters the union result back to
     each request.

Consistency contract (docs/SERVING.md): the locked path's plan is
computed optimistically outside the lock against a `topology_version`
snapshot and REVALIDATED under the lock at take time, exactly like
`Worker.pull` (PR 1's staged-pull discipline); the per-class gathers
are single device programs enqueued under the lock, so every key in a
coalesced batch is read from the same pool state (no torn batches — a
concurrent push is a whole program ordered before or after the gather,
never interleaved). The replica path keeps the same contract through
its write-epoch validation (serve/replica.py module docstring): a
batch carrying `after` ordering futures, an uncovered key, a moved
topology, or any bumped epoch falls back to the locked path. A serve
lookup is therefore bit-identical to a plain `Worker.pull` of the same
keys at the same point in dispatch order, across concurrent
relocations and sync rounds (pinned by tests/test_serve.py's storm
tests, replica path included).
"""
from __future__ import annotations

import collections
import itertools
import time
from typing import Dict, List, Optional

import numpy as np

from ..exec.executor import dispatch_gate
from ..obs.metrics import BATCH_SIZE_BOUNDS, SERVE_LATENCY_BOUNDS_S
from .admission import AdmissionQueue, LookupRequest, ServeDegradedError
from .bags import BagLookupRequest, plan_bag_batch, pool_bags_host


class LookupBatcher:
    """Owns the dispatch logic (drain programs on the per-lane executor
    streams); one per ServePlane."""

    def __init__(self, server, opts, queue: AdmissionQueue,
                 shard: int = 0):
        self.server = server
        self.opts = opts
        self.queue = queue
        # the shard serve lookups route from: a local replica there is
        # preferred, otherwise the owner row is gathered directly (the
        # pools are one global sharded array, so any shard's rows are
        # one gather away in a single process)
        self.shard = int(shard)
        # sharded dispatch (ISSUE 9): one drain stream per admission
        # lane. Stream 0 keeps the historical name `serve` so existing
        # drains/metrics/tooling see the single-dispatcher default
        # unchanged.
        self.dispatchers = max(1, int(getattr(opts, "serve_dispatchers",
                                              1)))
        self.streams = ["serve"] + [f"serve.{i}"
                                    for i in range(1, self.dispatchers)]
        # wall-clock start of the batch each dispatcher is currently
        # serving (None = parked/idle). Written only by the owning
        # drain; read lock-free by the health monitor's wedge probe.
        self._busy_since: List[Optional[float]] = \
            [None] * self.dispatchers
        # lane assignment policy (ServeSession.lookup): by length class
        # on multi-class servers (per-length-class program queues —
        # long-row gathers stay off the short rows' stream), else
        # round-robin so single-class load still spreads over N
        self._rr = itertools.count()
        # read-only serve replica (serve/replica.py); attached by
        # ServePlane when --sys.serve.replica_rows > 0, else None (the
        # fast path costs one attribute check)
        self.replica = None
        # the EFFECTIVE micro-batch window: initialized from the static
        # knob and — only when --sys.serve.slo_ms is set — adapted by
        # the SLO controller (obs/slo.py) so tails track the target.
        # With no SLO target nothing ever writes it, so the static-knob
        # path behaves exactly as before
        self.max_wait_us = int(opts.serve_max_wait_us)
        # per-priority-class effective windows (ISSUE 20 satellite;
        # --sys.serve.slo_ms class overrides). None — the default, and
        # the ONLY value without overrides — keeps the take() path
        # byte-identical; set by ServePlane to {prio: wait_us}, each
        # entry walked independently by the SLO controller. The
        # bounded sample ring feeds the controller's per-class
        # percentiles (plain (t_mono, latency_s, prio) tuples — no
        # dynamic per-class registry names, APM007 stays closed).
        self.class_wait_us: Optional[Dict[int, int]] = None
        self._class_samples: Optional[collections.deque] = None
        self._running = False
        reg = server.obs
        # shared=True: a plane rebuilt on the same server reuses the
        # metrics (single-registration discipline, docs/OBSERVABILITY.md)
        self.c_lookups = reg.counter("serve.lookups_total", shared=True)
        self.c_batches = reg.counter("serve.batches_total", shared=True)
        self.c_keys = reg.counter("serve.keys_total", shared=True)
        self.c_keys_unique = reg.counter("serve.keys_deduped_total",
                                         shared=True)
        # replica-path accounting (schema v8): batches served lock-free
        # from the snapshot, and the hit-rate gauge the bench/guard
        # quote (present-but-inert when no replica is attached)
        self.c_replica_hits = reg.counter("serve.replica_hits_total",
                                          shared=True)
        if reg.enabled:
            reg.gauge("serve.replica_hit_rate", shared=True,
                      fn=self.replica_hit_rate)
        self.h_latency = reg.histogram("serve.latency_s",
                                       bounds=SERVE_LATENCY_BOUNDS_S,
                                       shared=True)
        self.h_batch = reg.histogram("serve.batch_size", unit="requests",
                                     bounds=BATCH_SIZE_BOUNDS, shared=True)
        # bag-read accounting (ISSUE 16; schema v12): requests and
        # pooled vectors delivered, plus which path produced the bits —
        # fused device gather+pool batches vs host-pooled batches
        # (replica snapshot hit or flat-union fallback), the replica
        # subset counted separately for the hit-rate story
        self.c_bag_lookups = reg.counter("serve.bag_lookups_total",
                                         shared=True)
        self.c_bag_pooled = reg.counter("serve.bag_pooled_total",
                                        shared=True)
        self.c_bag_fused = reg.counter("serve.bag_fused_total",
                                       shared=True)
        self.c_bag_hostpool = reg.counter("serve.bag_hostpool_total",
                                          shared=True)
        self.c_bag_replica_hits = reg.counter(
            "serve.bag_replica_hits_total", shared=True)

    def replica_hit_rate(self) -> float:
        """Fraction of coalesced batches served from the read-only
        replica snapshot (0 with no replica attached)."""
        b = float(self.c_batches.value)
        return float(self.c_replica_hits.value) / b if b else 0.0

    # -- lane assignment (called by ServeSession) ----------------------------

    def assign_lane(self, keys: np.ndarray) -> int:
        """Admission lane for a request: its length class on
        multi-class servers (so each class's gathers queue on their own
        stream), round-robin otherwise. With one dispatcher everything
        is lane 0 — the pre-PR path."""
        if self.dispatchers == 1:
            return 0
        srv = self.server
        if len(srv.stores) > 1 and len(keys):
            return int(srv.ab.key_class[keys[0]]) % self.dispatchers
        return next(self._rr) % self.dispatchers

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self.queue.set_kick(self._kick)
        for lane in range(self.dispatchers):
            self._kick(lane)  # drain anything admitted before start

    def stop(self) -> None:
        """Close the queue (failing queued requests loudly) and drain
        every dispatcher stream under ONE 30 s bound. A drain program
        that does not finish within the bound is WEDGED (e.g. blocked
        on a dead remote owner's pull future) and still reads through
        the server's pools — proceeding into pool teardown would be a
        use-after-teardown, so this fail-stops loudly instead
        (docs/failure_handling.md) and keeps `_running` set
        (is_alive()/readiness stay truthful about the live reader)."""
        self.queue.set_kick(None)
        self.queue.close()
        ex = self.server.exec
        if not ex.closed and not ex.drain_streams(self.streams,
                                                  timeout=30):
            from ..utils import alog
            alog("[serve] dispatcher(s) failed to exit within 30s — "
                 "wedged mid-dispatch (dead remote owner?)")
            raise RuntimeError(
                "serve dispatcher wedged: did not exit within 30s "
                "of queue close; refusing to proceed into pool "
                "teardown under a live reader")
        self._running = False

    def is_alive(self) -> bool:
        """Dispatch capability: started, not stopped, and the executor
        that runs the drain programs is still open."""
        return self._running and not self.server.exec.closed

    def wedged_dispatchers(self, bound_s: float) -> List[int]:
        """Dispatchers that have been serving ONE batch for longer than
        `bound_s` (ISSUE 9 satellite: per-dispatcher liveness). Reads
        the busy stamps lock-free — a wedged drain cannot be asked to
        report, so readiness must never block on it."""
        now = time.monotonic()
        return [i for i, t in enumerate(self._busy_since)
                if t is not None and now - t > bound_s]

    # -- dispatchers ---------------------------------------------------------

    def _kick(self, lane: int = 0) -> None:
        """Queue one drain for `lane` on its stream (coalesced: kicks
        landing while that drain is queued are absorbed; a kick during
        a RUNNING drain queues the next one, so no admitted request is
        ever left undrained)."""
        if self._running:
            self.server.exec.submit(
                self.streams[lane], lambda: self._drain(lane),
                label=f"serve.drain.{lane}",
                coalesce_key=f"serve.drain.{lane}")

    def _drain(self, lane: int) -> None:
        """Serve micro-batches until the lane is empty (one executor
        program; FIFO on the lane's stream). The non-blocking take
        still LINGERS up to the micro-batch window after claiming a
        first request — that linger is the coalescing lever and counts
        as genuine stream-busy time."""
        srv = self.server
        if srv.fault is not None:
            # ISSUE 10 injection point: fires BEFORE any request is
            # claimed, so a failed drain program sheds nobody — the
            # executor's retry policy re-runs the drain and every
            # admitted request is still served
            try:
                srv.fault.fire("serve.drain")
            except BaseException:
                # re-kick the lane FIRST (coalesced, short delay):
                # kicks that landed while this program was queued were
                # absorbed into it, so if the executor's retry budget
                # exhausts and this program dies, the follow-up drain
                # queued here still serves every admitted request — a
                # no-deadline lookup must never hang on a dead drain
                if self._running:
                    srv.exec.submit(
                        self.streams[lane],
                        lambda: self._drain(lane),
                        label=f"serve.drain.{lane}",
                        coalesce_key=f"serve.drain.{lane}",
                        delay=0.02)
                raise
        max_batch = self.opts.serve_max_batch
        while True:
            # re-read per batch: the SLO controller adapts max_wait_us
            # between batches and the next window must honor it
            max_wait_s = self.max_wait_us * 1e-6
            cw = self.class_wait_us
            reqs = self.queue.take(
                max_batch, max_wait_s, block=False, lane=lane,
                wait_s_by_prio=(
                    {p: w * 1e-6 for p, w in cw.items()}
                    if cw is not None else None))
            if not reqs:
                return  # empty (or closed): park until the next kick
            self._busy_since[lane] = time.monotonic()
            pol = srv.policy
            if pol is not None:
                # ISSUE 18: how this batch's coalescing window closed
                # — filled to max_batch (size-limited) or dispatched
                # with room left when the window expired
                # (window-limited). The live denominator the serve
                # batch-window policy's shadow A/B reads against
                # (docs/POLICY.md runbook); one `is None` check when
                # the plane is off (the r7 skip-wrapper discipline).
                pol.note_batch(len(reqs) < max_batch)
            try:
                self._serve_batch(reqs)
            except (KeyboardInterrupt, SystemExit):
                # interpreter/process teardown is NOT a request
                # failure: shed the claimed batch so no waiter hangs,
                # then PROPAGATE (ISSUE 9 satellite — recording these
                # as request errors used to swallow the interrupt and
                # keep the dispatcher serving)
                for r in reqs:
                    if not r._done.is_set():
                        if r.tenant is not None:
                            r.tenant.c_shed.inc()
                        self.queue.c_shed.inc()
                        r.fail(RuntimeError(
                            "serve dispatcher interrupted "
                            "(KeyboardInterrupt/SystemExit): claimed "
                            "batch shed"))
                raise
            except BaseException as e:  # noqa: BLE001 — the dispatcher
                # must outlive any one batch: fail the batch's waiters
                # loudly (never leave a claimed request undelivered) and
                # keep serving
                for r in reqs:
                    if not r._done.is_set():
                        r.fail(e)
            finally:
                self._busy_since[lane] = None

    def _serve_batch(self, reqs: List[LookupRequest]) -> None:
        srv = self.server
        # degraded window (ISSUE 10): requests admitted BEFORE the
        # window opened are shed here with the same distinct error the
        # session door uses — a degraded server never dispatches a
        # gather (no torn or stale read; the restore is mutating the
        # pools under the lock this batch would otherwise take)
        reason = srv._degraded_reason
        if reason is not None:
            for r in reqs:
                self.queue.c_degraded.inc()
                self.queue.c_shed.inc()
                if r.tenant is not None:
                    r.tenant.c_shed.inc()
                r.fail(ServeDegradedError(
                    f"serve degraded: {reason} — queued lookup shed"))
            return
        fl = srv.flight
        t_dispatch = time.perf_counter()  # batch window closes, the
        # coalesced lookup starts (flight.batch -> flight.program edge)
        self.c_batches.inc()
        self.h_batch.observe(float(len(reqs)))
        # bag reads (ISSUE 16) coalesce separately: their reply is
        # pooled vectors, not per-key rows, so they cannot share the
        # flat union scatter below. A failed bag batch fails only its
        # own waiters; the flat requests still get served.
        bag_reqs = [r for r in reqs if isinstance(r, BagLookupRequest)]
        if bag_reqs:
            try:
                self._serve_bag_batch(bag_reqs, fl, t_dispatch)
            except (KeyboardInterrupt, SystemExit):
                for r in reqs:
                    if not r._done.is_set():
                        r.fail(RuntimeError(
                            "serve dispatcher interrupted "
                            "(KeyboardInterrupt/SystemExit): claimed "
                            "batch shed"))
                raise
            except BaseException as e:  # noqa: BLE001 — see _drain
                for r in bag_reqs:
                    if not r._done.is_set():
                        r.fail(e)
            reqs = [r for r in reqs
                    if not isinstance(r, BagLookupRequest)]
            if not reqs:
                return
        if len(reqs) == 1:
            allk = reqs[0].keys
        else:
            allk = np.concatenate([r.keys for r in reqs])
        union = np.unique(allk)
        if srv.tier is not None:
            # tiered storage: consult residency before planning — bump
            # the union keys' access scores and queue promotion of the
            # cold ones, so the device-hot set adapts to serve load (the
            # gather itself serves cold rows correctly through the cold
            # path either way; tier.serve_cold_keys counts them)
            srv.tier.note_serve(union)
        after = tuple(f for r in reqs for f in r.after)
        # read fast path (ISSUE 9): a batch with no cross-process write
        # ordering may be served lock-free from the replica snapshot;
        # any validation failure inside try_serve falls back here
        served = None
        rep = self.replica
        if rep is not None and not after:
            served = rep.try_serve(union)
        if served is not None:
            flat, t_cutoff = served
            self.c_replica_hits.inc()
            # lock-free hit: no dispatch/device segment — the flight
            # breakdown's enqueue stamp collapses onto the dispatch
            # point; the freshness probe keeps the SNAPSHOT's
            # under-lock stamp as its read-order cutoff (the served
            # bits are exactly as fresh as the snapshot's gather)
            t_enqueued = t_dispatch
        else:
            try:
                flat, t_enqueued = self._lookup_union(union, after)
                t_cutoff = t_enqueued
            except (KeyboardInterrupt, SystemExit):
                for r in reqs:
                    if not r._done.is_set():
                        r.fail(RuntimeError(
                            "serve dispatcher interrupted "
                            "(KeyboardInterrupt/SystemExit): claimed "
                            "batch shed"))
                raise  # _drain propagates (satellite fix)
            except BaseException as e:  # noqa: BLE001 — fail every waiter
                for r in reqs:
                    r.fail(e)
                return
        # scatter the deduplicated union back to each request's keys
        # (duplicates within a request fan out here, like Worker.pull)
        from ..parallel.pm import _offsets, _select_flat
        lens_u = srv.value_lengths[union]
        offs_u = _offsets(lens_u)
        self.c_keys_unique.inc(len(union))
        now = time.perf_counter()
        if fl is not None:
            # stamp the program timestamps on every member trace and
            # record the batch-membership slices BEFORE delivering:
            # deliver wakes the client, whose finish_lookup closes the
            # flow and must see a fully-stamped trace
            fl.record_serve_batch(
                [r.trace for r in reqs if r.trace is not None],
                t_dispatch, t_enqueued, now, n_requests=len(reqs),
                n_keys=len(allk), n_unique=len(union))
            # freshness probe: this union is a servable read of any
            # probed key whose push was enqueued before this gather —
            # or, on the replica path, before the SNAPSHOT's gather
            # (obs/flight.py; t_cutoff orders the two either way)
            fl.freshness.note_read(union, t_cutoff)
        for r in reqs:
            pos = np.searchsorted(union, r.keys)
            if r.trace is not None:
                r.trace.t_deliver = time.perf_counter()
            r.deliver(_select_flat(flat, offs_u, lens_u, pos))
            self.c_lookups.inc()
            self.c_keys.inc(len(r.keys))
            if r.tenant is not None:
                r.tenant.c_served.inc()
            self.h_latency.observe(now - r.t0)
            cs = self._class_samples
            if cs is not None:
                cs.append((now, now - r.t0, r.priority))

    def _lookup_union(self, keys: np.ndarray, after):
        """One coalesced pull of the (unique, sorted) union batch — the
        `Worker._pull_op` sequence minus per-worker staging: optimistic
        plan via the shared routing-plan cache, topology_version
        revalidation under the lock, `Server._pull` dispatch. Returns
        `(flat, t_enqueued)`: the perf_counter stamp taken right after
        the device gather programs are ENQUEUED (the flight breakdown's
        dispatch/device split; assembly below it blocks on the device)."""
        srv = self.server
        with srv._span("serve.lookup"):
            plan, tv = None, -1
            if srv.opts.optimistic_routing:
                tv = srv.topology_version
                plan = srv._plan_cached(
                    "pull", self.shard, keys, tv,
                    lambda: srv._plan_pull(keys, self.shard))
            with srv._lock:
                if plan is not None and srv.topology_version != tv:
                    plan = None  # topology moved underneath us: re-plan
                groups, _, remote = srv._pull(keys, self.shard,
                                              after=after, plan=plan)
                # stamped under the lock so it totally orders against
                # FreshnessProbe.push_visible stamps (same lock)
                t_enqueued = time.perf_counter()
            return (srv._assemble_flat(keys, groups, remote=remote),
                    t_enqueued)

    # -- bag reads (ISSUE 16) ------------------------------------------------

    def _serve_bag_batch(self, reqs: List[BagLookupRequest], fl,
                         t_dispatch: float) -> None:
        """Serve a coalesced batch of bag lookups. Path choice per
        batch (serve/bags.py module docstring — the returned bits are
        identical on every path):

          1. replica snapshot fully covers the member-key union and no
             `after` ordering → host-pool over the snapshot rows
             (lock-free, zero device dispatches);
          2. `--sys.serve.bags` on and single-process (every member is
             one gather away in the global pools) → ONE fused
             gather_pool program per (length class, pooling) under the
             server lock — only pooled vectors cross the device
             boundary;
          3. otherwise (multi-process — members may live off-process —
             or the knob is off) → the flat union gather
             (`_lookup_union`, which orders remote members through the
             DCN channel correctly) + host pool."""
        srv = self.server
        allk = np.concatenate([r.keys for r in reqs]) \
            if len(reqs) > 1 else reqs[0].keys
        union = np.unique(allk)
        if srv.tier is not None:
            srv.tier.note_serve(union)
        after = tuple(f for r in reqs for f in r.after)
        groups, slices = plan_bag_batch(reqs, srv.ab.key_class)
        pooled = None
        rep = self.replica
        served = rep.try_serve(union) \
            if rep is not None and not after else None
        if served is not None:
            flat, t_cutoff = served
            self.c_bag_replica_hits.inc()
            self.c_bag_hostpool.inc()
            pooled = self._pool_from_flat(flat, union, groups)
            t_enqueued = t_dispatch
        else:
            fused = (bool(getattr(self.opts, "serve_bags", True))
                     and srv.glob is None and not after)
            costs = getattr(srv, "costs", None)
            if fused and costs is not None:
                # measured-cost consult (ops/costs.py): host-pool this
                # batch only if the table measures the flat gather +
                # host pool cheaper for EVERY group's shape; a missing
                # entry (None) keeps the fused default for its group
                verdicts = [costs.prefer_fused(
                    int(srv.value_lengths[g["keys"][0]]),
                    len(g["keys"]),
                    np.dtype(srv.stores[gkey[0]].dtype).name,
                    gkey[1]) for gkey, g in groups.items()]
                if verdicts and all(v is False for v in verdicts):
                    fused = False
                    costs.c_overrides.inc()
                dc = srv.decisions
                if dc is not None and verdicts:
                    # ISSUE 17: the measured-cost dispatch verdict for
                    # this bag batch (outcome immediate — the table is
                    # already measured)
                    dc.record_costs(
                        fused, len(verdicts), len(union),
                        sum(1 for v in verdicts if v is False),
                        sum(1 for v in verdicts if v is None))
            if fused:
                dev, t_enqueued = self._lookup_bags_fused(groups)
                pooled = {k: np.asarray(v)[:groups[k]["nbags"]]
                          for k, v in dev.items()}
                t_cutoff = t_enqueued
                self.c_bag_fused.inc()
            else:
                flat, t_enqueued = self._lookup_union(union, after)
                t_cutoff = t_enqueued
                self.c_bag_hostpool.inc()
                pooled = self._pool_from_flat(flat, union, groups)
        now = time.perf_counter()
        if fl is not None:
            fl.record_serve_batch(
                [r.trace for r in reqs if r.trace is not None],
                t_dispatch, t_enqueued, now, n_requests=len(reqs),
                n_keys=len(allk), n_unique=len(union))
            fl.freshness.note_read(union, t_cutoff)
        for r, rs in zip(reqs, slices):
            parts = [np.ascontiguousarray(
                pooled[g][s:s + nb]).ravel() for g, s, nb in rs]
            if r.trace is not None:
                r.trace.t_deliver = time.perf_counter()
            r.deliver(np.concatenate(parts)
                      if len(parts) > 1 else parts[0])
            self.c_bag_lookups.inc()
            self.c_bag_pooled.inc(sum(nb for _, _, nb in rs))
            if r.tenant is not None:
                r.tenant.c_served.inc()
            self.h_latency.observe(now - r.t0)
            cs = self._class_samples
            if cs is not None:
                cs.append((now, now - r.t0, r.priority))

    def _lookup_bags_fused(self, groups):
        """Dispatch one fused gather_pool per (length class, pooling)
        group — route the member coordinates and enqueue every group's
        program back-to-back under ONE dispatch-gate hold inside the
        server lock (the same contiguous-enqueue discipline
        `Server._pull` applies to multi-class flat batches). Only
        called single-process (`srv.glob is None`), where every member
        row lives in the global pools. Returns `({gkey: device pooled
        matrix}, t_enqueued)` — readback happens on the caller, outside
        the lock."""
        srv = self.server
        from ..core.store import OOB
        with srv._span("serve.bag_lookup"):
            with srv._lock:
                dev = {}
                with dispatch_gate():
                    for gkey, g in groups.items():
                        cid, pooling = gkey
                        o_sh, o_sl, c_sh, c_sl, use_c, _, _ = \
                            srv._route(g["keys"], self.shard,
                                       record=False)
                        o_sl = np.where(use_c, OOB,
                                        o_sl).astype(np.int32)
                        dev[gkey] = srv.stores[cid].gather_pool(
                            o_sh, o_sl, c_sh, c_sl, use_c, g["seg"],
                            g["nbags"], pooling=pooling)
                t_enqueued = time.perf_counter()
        return dev, t_enqueued

    def _pool_from_flat(self, flat, union, groups):
        """Host-pool each group's bags out of a flat union value buffer
        (replica snapshot rows or a `_lookup_union` result) — the
        bit-identical twin of the fused device path (pool_bags_host)."""
        srv = self.server
        from ..parallel.pm import _offsets, _select_flat
        lens_u = srv.value_lengths[union]
        offs_u = _offsets(lens_u)
        out = {}
        for gkey, g in groups.items():
            ks = g["keys"]
            pos = np.searchsorted(union, ks)
            L = int(srv.value_lengths[ks[0]])
            rows = _select_flat(flat, offs_u, lens_u,
                                pos).reshape(len(ks), L)
            out[gkey] = pool_bags_host(rows, g["seg"], g["nbags"],
                                       gkey[1])
        return out
