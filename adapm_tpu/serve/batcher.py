"""Micro-batching coalescer: many concurrent lookups -> one fused gather.

DLRM-style inference is dominated by the embedding lookup path, and a
dedicated request-coalescing layer in front of the parameter store is
the standard lever (GraphVite's batched sample/lookup pipeline,
PAPERS.md; "Dissecting Embedding Bag Performance in DLRM Inference").
The `LookupBatcher` runs one dispatcher thread that

  1. takes up to `--sys.serve.max_batch` requests from the admission
     queue, lingering at most `--sys.serve.max_wait_us` after the first
     (the micro-batch window — while a batch's gather is in flight the
     queue refills, so sustained load coalesces without waiting);
  2. DEDUPLICATES the union key set (concurrent clients hit the same hot
     rows; the device gathers one row per unique key, not per request);
  3. dispatches ONE fused gather per length class through the exact
     Pull machinery the training path uses — the routing-plan cache,
     `Server._plan_pull`, and `Server._pull` under the server lock —
     and scatters the union result back to each request.

Consistency contract (docs/SERVING.md): the plan is computed
optimistically outside the lock against a `topology_version` snapshot
and REVALIDATED under the lock at take time, exactly like `Worker.pull`
(PR 1's staged-pull discipline); the per-class gathers are single
device programs enqueued under the lock, so every key in a coalesced
batch is read from the same pool state (no torn batches — a concurrent
push is a whole program ordered before or after the gather, never
interleaved). A serve lookup is therefore bit-identical to a plain
`Worker.pull` of the same keys at the same point in dispatch order,
across concurrent relocations and sync rounds (pinned by
tests/test_serve.py's storm test).
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from ..obs.metrics import BATCH_SIZE_BOUNDS, SERVE_LATENCY_BOUNDS_S
from .admission import AdmissionQueue, LookupRequest


class LookupBatcher:
    """Owns the dispatcher thread; one per ServePlane."""

    def __init__(self, server, opts, queue: AdmissionQueue,
                 shard: int = 0):
        self.server = server
        self.opts = opts
        self.queue = queue
        # the shard serve lookups route from: a local replica there is
        # preferred, otherwise the owner row is gathered directly (the
        # pools are one global sharded array, so any shard's rows are
        # one gather away in a single process)
        self.shard = int(shard)
        self._thread: Optional[threading.Thread] = None
        reg = server.obs
        # shared=True: a plane rebuilt on the same server reuses the
        # metrics (single-registration discipline, docs/OBSERVABILITY.md)
        self.c_lookups = reg.counter("serve.lookups_total", shared=True)
        self.c_batches = reg.counter("serve.batches_total", shared=True)
        self.c_keys = reg.counter("serve.keys_total", shared=True)
        self.c_keys_unique = reg.counter("serve.keys_deduped_total",
                                         shared=True)
        self.h_latency = reg.histogram("serve.latency_s",
                                       bounds=SERVE_LATENCY_BOUNDS_S,
                                       shared=True)
        self.h_batch = reg.histogram("serve.batch_size", unit="requests",
                                     bounds=BATCH_SIZE_BOUNDS, shared=True)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="adapm-serve")
        self._thread.start()

    def stop(self) -> None:
        """Close the queue (failing queued requests loudly) and join.
        A dispatcher that does not exit within the join bound is WEDGED
        (e.g. blocked on a dead remote owner's pull future) and still
        reads through the server's pools — proceeding into pool
        teardown would be a use-after-teardown, so this fail-stops
        loudly instead (docs/failure_handling.md) and keeps the thread
        handle (is_alive()/readiness stay truthful)."""
        self.queue.close()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
            if t.is_alive():
                from ..utils import alog
                alog("[serve] dispatcher failed to exit within 30s — "
                     "wedged mid-dispatch (dead remote owner?)")
                raise RuntimeError(
                    "serve dispatcher wedged: did not exit within 30s "
                    "of queue close; refusing to proceed into pool "
                    "teardown under a live reader")
            self._thread = None

    def is_alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- dispatcher ----------------------------------------------------------

    def _loop(self) -> None:
        max_batch = self.opts.serve_max_batch
        max_wait_s = self.opts.serve_max_wait_us * 1e-6
        while True:
            reqs = self.queue.take(max_batch, max_wait_s)
            if not reqs:
                return  # queue closed
            try:
                self._serve_batch(reqs)
            except BaseException as e:  # noqa: BLE001 — the dispatcher
                # must outlive any one batch: fail the batch's waiters
                # loudly (never leave a claimed request undelivered) and
                # keep serving
                for r in reqs:
                    if not r._done.is_set():
                        r.fail(e)

    def _serve_batch(self, reqs: List[LookupRequest]) -> None:
        srv = self.server
        self.c_batches.inc()
        self.h_batch.observe(float(len(reqs)))
        if len(reqs) == 1:
            allk = reqs[0].keys
        else:
            allk = np.concatenate([r.keys for r in reqs])
        union = np.unique(allk)
        if srv.tier is not None:
            # tiered storage: consult residency before planning — bump
            # the union keys' access scores and queue promotion of the
            # cold ones, so the device-hot set adapts to serve load (the
            # gather itself serves cold rows correctly through the cold
            # path either way; tier.serve_cold_keys counts them)
            srv.tier.note_serve(union)
        after = tuple(f for r in reqs for f in r.after)
        try:
            flat = self._lookup_union(union, after)
        except BaseException as e:  # noqa: BLE001 — fail every waiter
            for r in reqs:
                r.fail(e)
            return
        # scatter the deduplicated union back to each request's keys
        # (duplicates within a request fan out here, like Worker.pull)
        from ..parallel.pm import _offsets, _select_flat
        lens_u = srv.value_lengths[union]
        offs_u = _offsets(lens_u)
        self.c_keys_unique.inc(len(union))
        now = time.perf_counter()
        for r in reqs:
            pos = np.searchsorted(union, r.keys)
            r.deliver(_select_flat(flat, offs_u, lens_u, pos))
            self.c_lookups.inc()
            self.c_keys.inc(len(r.keys))
            self.h_latency.observe(now - r.t0)

    def _lookup_union(self, keys: np.ndarray, after) -> np.ndarray:
        """One coalesced pull of the (unique, sorted) union batch — the
        `Worker._pull_op` sequence minus per-worker staging: optimistic
        plan via the shared routing-plan cache, topology_version
        revalidation under the lock, `Server._pull` dispatch."""
        srv = self.server
        with srv._span("serve.lookup"):
            plan, tv = None, -1
            if srv.opts.optimistic_routing:
                tv = srv.topology_version
                plan = srv._plan_cached(
                    "pull", self.shard, keys, tv,
                    lambda: srv._plan_pull(keys, self.shard))
            with srv._lock:
                if plan is not None and srv.topology_version != tv:
                    plan = None  # topology moved underneath us: re-plan
                groups, _, remote = srv._pull(keys, self.shard,
                                              after=after, plan=plan)
            return srv._assemble_flat(keys, groups, remote=remote)
