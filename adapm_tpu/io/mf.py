"""Matrix-factorization data IO (reference apps/mf/io.h:125-266).

Supports MatrixMarket coordinate files (the reference's `.mma`/`.mmc`
format), plain "i j v" text, and synthetic low-rank generation. Data points
are partitioned into per-worker row blocks and, for DSGD, column blocks with
a worker x subepoch schedule (reference apps/mf/data.h:182-210).
"""
from __future__ import annotations

from typing import Tuple

import numpy as np


def read_coo(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int, int]:
    """Read a sparse matrix in MatrixMarket coordinate format (or bare
    "i j v" lines, 1-based like MM). Returns (rows, cols, vals, m, n)."""
    rows, cols, vals = [], [], []
    m = n = 0
    # only a %%MatrixMarket banner makes the first non-comment line a size
    # line; bare "i j v" files (even with integer values) are all data
    is_mm = False
    size_pending = False
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith("%"):
                if line.startswith("%%MatrixMarket"):
                    is_mm = True
                    size_pending = True
                continue
            parts = line.split()
            if is_mm and size_pending:
                m, n = int(parts[0]), int(parts[1])
                size_pending = False
                continue
            i, j = int(parts[0]) - 1, int(parts[1]) - 1
            v = float(parts[2]) if len(parts) > 2 else 1.0
            rows.append(i)
            cols.append(j)
            vals.append(v)
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    m = max(m, int(rows.max()) + 1 if len(rows) else 0)
    n = max(n, int(cols.max()) + 1 if len(cols) else 0)
    return rows, cols, vals, m, n


def write_dense(path: str, M: np.ndarray) -> None:
    """Write a dense factor matrix in MatrixMarket array format (the
    reference dumps W.mma / H.mma, matrix_factorization.cc:233-355)."""
    with open(path, "w") as f:
        f.write("%%MatrixMarket matrix array real general\n")
        f.write(f"{M.shape[0]} {M.shape[1]}\n")
        for v in M.T.ravel():  # MM array format is column-major
            f.write(f"{v}\n")


def read_dense(path: str) -> np.ndarray:
    with open(path) as f:
        lines = [ln.strip() for ln in f
                 if ln.strip() and not ln.startswith("%")]
    m, n = (int(x) for x in lines[0].split())
    vals = np.asarray([float(x) for x in lines[1:1 + m * n]], dtype=np.float32)
    return vals.reshape(n, m).T  # column-major -> [m, n]


def generate_synthetic(m: int, n: int, rank: int, nnz: int,
                       seed: int = 0, noise: float = 0.01):
    """Low-rank + noise observations; returns (rows, cols, vals, W, H)."""
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(m, rank)).astype(np.float32) / np.sqrt(rank)
    H = rng.normal(size=(n, rank)).astype(np.float32) / np.sqrt(rank)
    rows = rng.integers(0, m, nnz).astype(np.int64)
    cols = rng.integers(0, n, nnz).astype(np.int64)
    vals = ((W[rows] * H[cols]).sum(-1)
            + noise * rng.normal(size=nnz)).astype(np.float32)
    return rows, cols, vals, W, H


def partition_points(rows: np.ndarray, num_parts: int, m: int) -> np.ndarray:
    """Assign each data point to a worker by contiguous row block (reference
    partitions training points by row ranges per process, mf/io.h:125+).
    Returns per-point part ids."""
    block = (m + num_parts - 1) // num_parts
    return np.minimum(rows // block, num_parts - 1).astype(np.int32)


def column_block(cols: np.ndarray, num_blocks: int, n: int) -> np.ndarray:
    block = (n + num_blocks - 1) // num_blocks
    return np.minimum(cols // block, num_blocks - 1).astype(np.int32)


def dsgd_schedule(num_workers: int, epoch: int, seed: int = 7) -> np.ndarray:
    """DSGD block schedule: schedule[subepoch, worker] = column block, a
    random derangement-free permutation per subepoch such that within each
    subepoch all workers touch disjoint column blocks (reference WOR schedule,
    apps/mf/data.h:182-210). Returns [num_workers, num_workers]."""
    rng = np.random.default_rng(seed + epoch)
    base = rng.permutation(num_workers)
    out = np.empty((num_workers, num_workers), dtype=np.int64)
    for s in range(num_workers):
        out[s] = (base + s) % num_workers
    return out
