"""Data IO: readers, partitioners, and synthetic-data generators for the
bundled apps (reference apps/mf/io.h, word2vec.cc corpus reader, kge.cc
dataset loader)."""
