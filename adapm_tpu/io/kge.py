"""Knowledge-graph triple IO (reference apps/knowledge_graph_embeddings.cc
dataset loading + filtered-eval index construction, kge.cc:544-775).

Triple files are whitespace-separated integer id lines "s r o" (the
reference's del format). Filters map (s, r) -> {o} and (r, o) -> {s} over
all splits, for filtered MRR / Hits@k.
"""
from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, Optional, Set, Tuple

import numpy as np


@dataclasses.dataclass
class TripleDataset:
    num_entities: int
    num_relations: int
    train: np.ndarray            # [N, 3] int64 (s, r, o)
    valid: Optional[np.ndarray] = None
    test: Optional[np.ndarray] = None

    def filters(self) -> Tuple[Dict, Dict]:
        """(s,r)->set(o), (r,o)->set(s) over all splits (filtered eval
        excludes *known true* triples from the ranking, kge.cc Evaluator)."""
        sr_o: Dict[Tuple[int, int], Set[int]] = defaultdict(set)
        ro_s: Dict[Tuple[int, int], Set[int]] = defaultdict(set)
        for split in (self.train, self.valid, self.test):
            if split is None:
                continue
            for s, r, o in split:
                sr_o[(int(s), int(r))].add(int(o))
                ro_s[(int(r), int(o))].add(int(s))
        return dict(sr_o), dict(ro_s)


def read_triples(path: str) -> np.ndarray:
    out = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 3:
                out.append((int(parts[0]), int(parts[1]), int(parts[2])))
    return np.asarray(out, dtype=np.int64).reshape(-1, 3)


def load_dataset(train_path: str, valid_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 num_entities: Optional[int] = None,
                 num_relations: Optional[int] = None) -> TripleDataset:
    train = read_triples(train_path)
    valid = read_triples(valid_path) if valid_path else None
    test = read_triples(test_path) if test_path else None
    splits = [t for t in (train, valid, test) if t is not None and len(t)]
    all_t = np.concatenate(splits) if splits else train
    E = num_entities or int(max(all_t[:, 0].max(), all_t[:, 2].max())) + 1
    R = num_relations or int(all_t[:, 1].max()) + 1
    return TripleDataset(E, R, train, valid, test)


def generate_synthetic(num_entities: int = 120, num_relations: int = 8,
                       n_train: int = 1500, n_valid: int = 100,
                       n_test: int = 100, seed: int = 0) -> TripleDataset:
    """Random KG with learnable structure: each relation r is a fixed
    permutation + small cluster noise, so (s, r) largely determines o and
    embeddings can reach good filtered MRR."""
    rng = np.random.default_rng(seed)
    perms = [rng.permutation(num_entities) for _ in range(num_relations)]

    def draw(n):
        s = rng.integers(0, num_entities, n)
        r = rng.integers(0, num_relations, n)
        o = np.array([perms[ri][si] for si, ri in zip(s, r)])
        # noise: a few percent of objects are random
        noise = rng.random(n) < 0.05
        o[noise] = rng.integers(0, num_entities, int(noise.sum()))
        return np.stack([s, r, o], axis=1).astype(np.int64)

    return TripleDataset(num_entities, num_relations,
                         draw(n_train), draw(n_valid), draw(n_test))
