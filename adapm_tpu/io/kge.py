"""Knowledge-graph triple IO (reference apps/knowledge_graph_embeddings.cc
dataset loading + filtered-eval index construction, kge.cc:544-775).

Triple files are whitespace-separated integer id lines "s r o" (the
reference's del format). Filters map (s, r) -> {o} and (r, o) -> {s} over
all splits, for filtered MRR / Hits@k.
"""
from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, Optional, Set, Tuple

import numpy as np


@dataclasses.dataclass
class TripleDataset:
    num_entities: int
    num_relations: int
    train: np.ndarray            # [N, 3] int64 (s, r, o)
    valid: Optional[np.ndarray] = None
    test: Optional[np.ndarray] = None
    # per-side generating-model ceilings (lowrank synthetic only)
    truth_mrr_o: Optional[float] = None
    truth_mrr_s: Optional[float] = None

    def filters(self) -> Tuple[Dict, Dict]:
        """(s,r)->set(o), (r,o)->set(s) over all splits (filtered eval
        excludes *known true* triples from the ranking, kge.cc Evaluator)."""
        sr_o: Dict[Tuple[int, int], Set[int]] = defaultdict(set)
        ro_s: Dict[Tuple[int, int], Set[int]] = defaultdict(set)
        for split in (self.train, self.valid, self.test):
            if split is None:
                continue
            for s, r, o in split:
                sr_o[(int(s), int(r))].add(int(o))
                ro_s[(int(r), int(o))].add(int(s))
        return dict(sr_o), dict(ro_s)


def read_triples(path: str) -> np.ndarray:
    out = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 3:
                out.append((int(parts[0]), int(parts[1]), int(parts[2])))
    return np.asarray(out, dtype=np.int64).reshape(-1, 3)


def load_dataset(train_path: str, valid_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 num_entities: Optional[int] = None,
                 num_relations: Optional[int] = None) -> TripleDataset:
    train = read_triples(train_path)
    valid = read_triples(valid_path) if valid_path else None
    test = read_triples(test_path) if test_path else None
    splits = [t for t in (train, valid, test) if t is not None and len(t)]
    all_t = np.concatenate(splits) if splits else train
    E = num_entities or int(max(all_t[:, 0].max(), all_t[:, 2].max())) + 1
    R = num_relations or int(all_t[:, 1].max()) + 1
    return TripleDataset(E, R, train, valid, test)


def generate_synthetic(num_entities: int = 120, num_relations: int = 8,
                       n_train: int = 1500, n_valid: int = 100,
                       n_test: int = 100, seed: int = 0) -> TripleDataset:
    """Random KG with learnable structure: each relation r is a fixed
    permutation + small cluster noise, so (s, r) largely determines o and
    embeddings can reach good filtered MRR."""
    rng = np.random.default_rng(seed)
    perms = [rng.permutation(num_entities) for _ in range(num_relations)]

    def draw(n):
        s = rng.integers(0, num_entities, n)
        r = rng.integers(0, num_relations, n)
        o = np.array([perms[ri][si] for si, ri in zip(s, r)])
        # noise: a few percent of objects are random
        noise = rng.random(n) < 0.05
        o[noise] = rng.integers(0, num_entities, int(noise.sum()))
        return np.stack([s, r, o], axis=1).astype(np.int64)

    return TripleDataset(num_entities, num_relations,
                         draw(n_train), draw(n_valid), draw(n_test))


def generate_lowrank(num_entities: int = 120, num_relations: int = 8,
                     n_train: int = 1500, n_valid: int = 100,
                     n_test: int = 100, dim_truth: int = 16,
                     temperature: float = 0.25,
                     seed: int = 0) -> Tuple[TripleDataset, float]:
    """KG drawn from a GROUND-TRUTH ComplEx model: for a random (s, r),
    o is sampled from softmax(z / temperature) where z is the true
    bilinear score (row-normalized). Unlike `generate_synthetic`'s random
    permutations (full-rank, adversarial for bilinear models), this graph
    IS low-rank by construction, so a trained ComplEx of dim >= dim_truth
    can approach the GENERATING model's own filtered MRR — which is the
    right ceiling, returned as the second element: sampling at finite
    temperature means even the truth cannot rank every sampled object
    first. The mid-scale quality harness asserts trained-MRR as a
    fraction of truth-MRR (docs/PERF.md)."""
    rng = np.random.default_rng(seed)
    d = dim_truth
    ent = rng.normal(size=(num_entities, d)) + \
        1j * rng.normal(size=(num_entities, d))
    rel = rng.normal(size=(num_relations, d)) + \
        1j * rng.normal(size=(num_relations, d))

    def zscores(s, r):
        q = ent[s] * rel[r]                            # [c, d] complex
        sc = np.real(q @ ent.conj().T)                 # [c, E]
        sc -= sc.mean(axis=1, keepdims=True)
        sc /= sc.std(axis=1, keepdims=True)
        return sc

    def draw(n):
        s = rng.integers(0, num_entities, n)
        r = rng.integers(0, num_relations, n)
        o = np.empty(n, dtype=np.int64)
        for lo in range(0, n, 4096):  # bound the [chunk, E] score matrix
            hi = min(lo + 4096, n)
            z = zscores(s[lo:hi], r[lo:hi]) / temperature
            g = rng.gumbel(size=z.shape)               # Gumbel-max trick
            o[lo:hi] = (z + g).argmax(axis=1)
        return np.stack([s, r, o], axis=1).astype(np.int64)

    tr, va, te = draw(n_train), draw(n_valid), draw(n_test)
    ds = TripleDataset(num_entities, num_relations, tr, va, te)

    # the ceiling: the truth model's own filtered MRR on test, BOTH sides
    # (the app's evaluate() corrupts subject and object alike). Note the
    # subject side is intrinsically weak for this generator — s is drawn
    # uniformly, so even the truth ranks it poorly at large E.
    sr_o, ro_s = ds.filters()

    def zscores_s(r, o):  # score of every candidate subject
        q = rel[r] * ent[o].conj()
        sc = np.real(ent @ q.T).T                      # [c, E]
        sc -= sc.mean(axis=1, keepdims=True)
        sc /= sc.std(axis=1, keepdims=True)
        return sc

    rr_o: list = []
    rr_s: list = []
    for lo in range(0, len(te), 4096):
        chunk = te[lo:lo + 4096]
        zo = zscores(chunk[:, 0], chunk[:, 1])
        zs = zscores_s(chunk[:, 1], chunk[:, 2])
        for i, (s, r, o) in enumerate(chunk):
            for z, true_e, flt, acc in (
                    (zo[i], int(o), sr_o.get((int(s), int(r)), ()), rr_o),
                    (zs[i], int(s), ro_s.get((int(r), int(o)), ()), rr_s)):
                better = int((z > z[true_e]).sum()) - sum(
                    1 for e in flt if e != true_e and z[e] > z[true_e])
                acc.append(1.0 / (1 + better))
    # per-side ceilings ride as attributes: the subject side is
    # information-free by construction at large E (s ~ uniform), so
    # mid-scale quality is judged against the OBJECT ceiling
    # (apps/.. result["mrr_o"] vs ds.truth_mrr_o)
    ds.truth_mrr_o = float(np.mean(rr_o))
    ds.truth_mrr_s = float(np.mean(rr_s))
    return ds, float(np.mean(rr_o + rr_s))
