"""Knowledge-graph triple IO (reference apps/knowledge_graph_embeddings.cc
dataset loading + filtered-eval index construction, kge.cc:544-775).

Triple files are whitespace-separated integer id lines "s r o" (the
reference's del format). Filters map (s, r) -> {o} and (r, o) -> {s} over
all splits, for filtered MRR / Hits@k.
"""
from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, Optional, Set, Tuple

import numpy as np


@dataclasses.dataclass
class TripleDataset:
    num_entities: int
    num_relations: int
    train: np.ndarray            # [N, 3] int64 (s, r, o)
    valid: Optional[np.ndarray] = None
    test: Optional[np.ndarray] = None
    # per-side generating-model ceilings (lowrank synthetic only)
    truth_mrr_o: Optional[float] = None
    truth_mrr_s: Optional[float] = None

    def filters(self) -> Tuple[Dict, Dict]:
        """(s,r)->set(o), (r,o)->set(s) over all splits (filtered eval
        excludes *known true* triples from the ranking, kge.cc Evaluator)."""
        sr_o: Dict[Tuple[int, int], Set[int]] = defaultdict(set)
        ro_s: Dict[Tuple[int, int], Set[int]] = defaultdict(set)
        for split in (self.train, self.valid, self.test):
            if split is None:
                continue
            for s, r, o in split:
                sr_o[(int(s), int(r))].add(int(o))
                ro_s[(int(r), int(o))].add(int(s))
        return dict(sr_o), dict(ro_s)


def read_triples(path: str) -> np.ndarray:
    out = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 3:
                out.append((int(parts[0]), int(parts[1]), int(parts[2])))
    return np.asarray(out, dtype=np.int64).reshape(-1, 3)


def load_dataset(train_path: str, valid_path: Optional[str] = None,
                 test_path: Optional[str] = None,
                 num_entities: Optional[int] = None,
                 num_relations: Optional[int] = None) -> TripleDataset:
    train = read_triples(train_path)
    valid = read_triples(valid_path) if valid_path else None
    test = read_triples(test_path) if test_path else None
    splits = [t for t in (train, valid, test) if t is not None and len(t)]
    all_t = np.concatenate(splits) if splits else train
    E = num_entities or int(max(all_t[:, 0].max(), all_t[:, 2].max())) + 1
    R = num_relations or int(all_t[:, 1].max()) + 1
    return TripleDataset(E, R, train, valid, test)


def generate_synthetic(num_entities: int = 120, num_relations: int = 8,
                       n_train: int = 1500, n_valid: int = 100,
                       n_test: int = 100, seed: int = 0) -> TripleDataset:
    """Random KG with learnable structure: each relation r is a fixed
    permutation + small cluster noise, so (s, r) largely determines o and
    embeddings can reach good filtered MRR."""
    rng = np.random.default_rng(seed)
    perms = [rng.permutation(num_entities) for _ in range(num_relations)]

    def draw(n):
        s = rng.integers(0, num_entities, n)
        r = rng.integers(0, num_relations, n)
        o = np.array([perms[ri][si] for si, ri in zip(s, r)])
        # noise: a few percent of objects are random
        noise = rng.random(n) < 0.05
        o[noise] = rng.integers(0, num_entities, int(noise.sum()))
        return np.stack([s, r, o], axis=1).astype(np.int64)

    return TripleDataset(num_entities, num_relations,
                         draw(n_train), draw(n_valid), draw(n_test))


def generate_lowrank(num_entities: int = 120, num_relations: int = 8,
                     n_train: int = 1500, n_valid: int = 100,
                     n_test: int = 100, dim_truth: int = 16,
                     temperature: float = 0.25,
                     seed: int = 0,
                     device: Optional[bool] = None
                     ) -> Tuple[TripleDataset, float]:
    """KG drawn from a GROUND-TRUTH ComplEx model: for a random (s, r),
    o is sampled from softmax(z / temperature) where z is the true
    bilinear score (row-normalized). Unlike `generate_synthetic`'s random
    permutations (full-rank, adversarial for bilinear models), this graph
    IS low-rank by construction, so a trained ComplEx of dim >= dim_truth
    can approach the GENERATING model's own filtered MRR — which is the
    right ceiling, returned as the second element: sampling at finite
    temperature means even the truth cannot rank every sampled object
    first. The mid-scale quality harness asserts trained-MRR as a
    fraction of truth-MRR (docs/PERF.md).

    `device` moves the per-chunk score matmul + Gumbel-max onto the JAX
    default device (auto at num_entities >= 20000): the [chunk, E]
    score matrix is matmul+argmax work a chip does in milliseconds,
    while the host numpy path needs ~150 s/chunk at E=50k (measured) —
    hours for an MRR@scale dataset. The truth MODEL (ent/rel) is drawn
    from the same numpy stream either way; the object draws use JAX's
    PRNG on the device path, so datasets at equal seeds differ between
    paths (small-E pinned tests keep the numpy stream).

    RNG-stream break (round 5, ADVICE r5 #3): the HOST path's object
    draw switched from `rng.gumbel` (float64) to a float32
    inverse-transform (`-log(-log(rng.random(float32)))`), which changes
    how the generator consumes the numpy bit stream. Host-path datasets
    at a given seed therefore differ from those generated by pre-r5
    builds — numbers pinned against older datasets (docs/PERF.md) are
    not bit-reproducible across that boundary, though the ratio-based
    tests tolerate it. Within any post-r5 build the host stream is
    deterministic as usual."""
    if device is None:
        device = num_entities >= 20_000
    if device:
        return _generate_lowrank_device(num_entities, num_relations,
                                        n_train, n_valid, n_test,
                                        dim_truth, temperature, seed)
    rng = np.random.default_rng(seed)
    d = dim_truth
    ent = rng.normal(size=(num_entities, d)) + \
        1j * rng.normal(size=(num_entities, d))
    rel = rng.normal(size=(num_relations, d)) + \
        1j * rng.normal(size=(num_relations, d))

    def zscores(s, r):
        q = ent[s] * rel[r]                            # [c, d] complex
        sc = np.real(q @ ent.conj().T)                 # [c, E]
        sc -= sc.mean(axis=1, keepdims=True)
        sc /= sc.std(axis=1, keepdims=True)
        return sc

    def draw(n):
        s = rng.integers(0, num_entities, n)
        r = rng.integers(0, num_relations, n)
        o = np.empty(n, dtype=np.int64)
        for lo in range(0, n, 4096):  # bound the [chunk, E] score matrix
            hi = min(lo + 4096, n)
            z = (zscores(s[lo:hi], r[lo:hi]) / temperature).astype(
                np.float32)
            # Gumbel-max trick; drawn in float32 (rng.gumbel is
            # float64-only and dominates generation time at E >= 50k)
            u = rng.random(size=z.shape, dtype=np.float32)
            np.clip(u, np.float32(1e-12), None, out=u)
            g = -np.log(-np.log(u))
            o[lo:hi] = (z + g).argmax(axis=1)
        return np.stack([s, r, o], axis=1).astype(np.int64)

    tr, va, te = draw(n_train), draw(n_valid), draw(n_test)
    ds = TripleDataset(num_entities, num_relations, tr, va, te)

    # the ceiling: the truth model's own filtered MRR on test, BOTH sides
    # (the app's evaluate() corrupts subject and object alike). Note the
    # subject side is intrinsically weak for this generator — s is drawn
    # uniformly, so even the truth ranks it poorly at large E.
    sr_o, ro_s = ds.filters()

    def zscores_s(r, o):  # score of every candidate subject
        q = rel[r] * ent[o].conj()
        sc = np.real(ent @ q.T).T                      # [c, E]
        sc -= sc.mean(axis=1, keepdims=True)
        sc /= sc.std(axis=1, keepdims=True)
        return sc

    rr_o: list = []
    rr_s: list = []
    for lo in range(0, len(te), 4096):
        chunk = te[lo:lo + 4096]
        _truth_rr_chunk(chunk, zscores(chunk[:, 0], chunk[:, 1]),
                        zscores_s(chunk[:, 1], chunk[:, 2]),
                        sr_o, ro_s, rr_o, rr_s)
    # per-side ceilings ride as attributes: the subject side is
    # information-free by construction at large E (s ~ uniform), so
    # mid-scale quality is judged against the OBJECT ceiling
    # (apps/.. result["mrr_o"] vs ds.truth_mrr_o)
    ds.truth_mrr_o = float(np.mean(rr_o))
    ds.truth_mrr_s = float(np.mean(rr_s))
    return ds, float(np.mean(rr_o + rr_s))


def _truth_rr_chunk(chunk: np.ndarray, zo: np.ndarray, zs: np.ndarray,
                    sr_o: Dict, ro_s: Dict, rr_o: list, rr_s: list) -> None:
    """Filtered reciprocal ranks of the TRUTH model for one test chunk,
    both sides — shared by the host and device generator paths so the
    rank rule (strict `>` + known-true exclusion) cannot diverge
    between the ceilings tests compare against."""
    for i, (s, r, o) in enumerate(chunk):
        for z, true_e, flt, acc in (
                (zo[i], int(o), sr_o.get((int(s), int(r)), ()), rr_o),
                (zs[i], int(s), ro_s.get((int(r), int(o)), ()), rr_s)):
            better = int((z > z[true_e]).sum()) - sum(
                1 for e in flt if e != true_e and z[e] > z[true_e])
            acc.append(1.0 / (1 + better))


def _generate_lowrank_device(num_entities: int, num_relations: int,
                             n_train: int, n_valid: int, n_test: int,
                             dim_truth: int, temperature: float,
                             seed: int) -> Tuple[TripleDataset, float]:
    """Device path of generate_lowrank (see its docstring): the truth
    model's complex bilinear scores as two real matmuls on the JAX
    default device, chunk shape fixed at [4096, E] so one compile covers
    every chunk."""
    import jax
    import jax.numpy as jnp

    E, R, d, T = num_entities, num_relations, dim_truth, temperature
    rng = np.random.default_rng(seed)
    # same numpy draws as the host path (model identity is shared)
    entc = rng.normal(size=(E, d)) + 1j * rng.normal(size=(E, d))
    relc = rng.normal(size=(R, d)) + 1j * rng.normal(size=(R, d))
    er = jnp.asarray(entc.real, jnp.float32)
    ei = jnp.asarray(entc.imag, jnp.float32)
    rr = jnp.asarray(relc.real, jnp.float32)
    ri = jnp.asarray(relc.imag, jnp.float32)
    C = 4096

    def _norm(sc):
        sc = sc - sc.mean(axis=1, keepdims=True)
        return sc / sc.std(axis=1, keepdims=True)

    # apm-lint: disable=APM008 offline hard-negative scorer (dataset
    # tooling, no Server/store in scope): backend-generic jax compute
    @jax.jit
    def z_o(s, r):
        # Re(<s, r, conj(e)>) for all e: q = ent[s] * rel[r];
        # Re(q @ conj(ent).T) = qr @ er.T + qi @ ei.T
        qr = er[s] * rr[r] - ei[s] * ri[r]
        qi = er[s] * ri[r] + ei[s] * rr[r]
        return _norm(qr @ er.T + qi @ ei.T)

    # apm-lint: disable=APM008 same offline scorer as z_o above
    @jax.jit
    def z_s(r, o):
        # candidate-subject scores: q = rel[r] * conj(ent[o]);
        # Re(ent @ q.T) = er @ qr.T - ei @ qi.T, transposed to [c, E]
        qr = rr[r] * er[o] + ri[r] * ei[o]
        qi = ri[r] * er[o] - rr[r] * ei[o]
        return _norm(qr @ er.T - qi @ ei.T)

    # apm-lint: disable=APM008 offline Gumbel draw over the scorer —
    # dataset tooling, not a PM data-plane dispatch site
    @jax.jit
    def draw_o(key, s, r):
        g = jax.random.gumbel(key, (C, E), dtype=jnp.float32)
        return jnp.argmax(z_o(s, r) / T + g, axis=1)

    def draw(n, split_id):
        s = rng.integers(0, E, n)
        r = rng.integers(0, R, n)
        o = np.empty(n, dtype=np.int64)
        key = jax.random.PRNGKey(seed * 3 + split_id)
        for ci, lo in enumerate(range(0, n, C)):
            hi = min(lo + C, n)
            sp = np.zeros(C, np.int64)
            rp = np.zeros(C, np.int64)
            sp[: hi - lo] = s[lo:hi]
            rp[: hi - lo] = r[lo:hi]
            oc = np.asarray(draw_o(jax.random.fold_in(key, ci), sp, rp))
            o[lo:hi] = oc[: hi - lo]
        return np.stack([s, r, o], axis=1).astype(np.int64)

    tr, va, te = draw(n_train, 0), draw(n_valid, 1), draw(n_test, 2)
    ds = TripleDataset(E, R, tr, va, te)

    # truth ceilings: scores for the (small) test split come back to the
    # host in [<=256, E] slabs for the filtered correction
    sr_o, ro_s = ds.filters()
    rr_acc: list = []
    rs_acc: list = []
    for lo in range(0, len(te), 256):
        chunk = te[lo:lo + 256]
        _truth_rr_chunk(chunk, np.asarray(z_o(chunk[:, 0], chunk[:, 1])),
                        np.asarray(z_s(chunk[:, 1], chunk[:, 2])),
                        sr_o, ro_s, rr_acc, rs_acc)
    ds.truth_mrr_o = float(np.mean(rr_acc))
    ds.truth_mrr_s = float(np.mean(rs_acc))
    return ds, float(np.mean(rr_acc + rs_acc))
