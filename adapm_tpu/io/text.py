"""Word2vec corpus IO (reference apps/word2vec.cc:83-144, 445-491):
vocabulary building with min-count pruning, sentence iteration as word-id
arrays, and a synthetic Zipf corpus generator for tests/smoke runs.
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, Iterator, List, Tuple

import numpy as np

MAX_SENTENCE_LEN = 1000  # reference word2vec.cc sentence chunking


def build_vocab(path: str, min_count: int = 5
                ) -> Tuple[List[str], np.ndarray, Dict[str, int]]:
    """Scan the corpus; return (words, counts, word->id). Words below
    min_count are dropped (reference vocab pruning); ids are ordered by
    descending count (w2v convention)."""
    counter: Counter = Counter()
    with open(path) as f:
        for line in f:
            counter.update(line.split())
    items = [(w, c) for w, c in counter.items() if c >= min_count]
    items.sort(key=lambda wc: (-wc[1], wc[0]))
    words = [w for w, _ in items]
    counts = np.asarray([c for _, c in items], dtype=np.int64)
    vocab = {w: i for i, w in enumerate(words)}
    return words, counts, vocab


def sentences(path: str, vocab: Dict[str, int],
              max_len: int = MAX_SENTENCE_LEN) -> Iterator[np.ndarray]:
    """Yield sentences as int64 word-id arrays; out-of-vocab words are
    skipped; long lines are chunked at max_len (reference behavior)."""
    with open(path) as f:
        for line in f:
            ids = [vocab[w] for w in line.split() if w in vocab]
            for i in range(0, len(ids), max_len):
                chunk = ids[i:i + max_len]
                if chunk:
                    yield np.asarray(chunk, dtype=np.int64)


def generate_synthetic_corpus(path: str, vocab_size: int = 200,
                              num_sentences: int = 500,
                              sentence_len: int = 20, seed: int = 0,
                              zipf_a: float = 1.2) -> None:
    """Zipf-distributed token stream with local co-occurrence structure
    (nearby tokens correlate), so SGNS has signal to learn."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(num_sentences):
            base = rng.zipf(zipf_a, size=sentence_len) % vocab_size
            # co-occurrence: every other token echoes its neighborhood
            for i in range(1, sentence_len, 3):
                base[i] = (base[i - 1] + 1) % vocab_size
            f.write(" ".join(f"w{t}" for t in base) + "\n")


def skipgram_pairs(sent: np.ndarray, window: int,
                   rng: np.random.Generator
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """(center, context) pairs with per-position random window shrink
    b ~ U[1, window] (reference PeekableRandom pre-computes these window
    draws, word2vec.cc:445-491). Returns (centers, contexts).

    Vectorized (VERDICT r3 item 8: the per-pair Python loop capped the
    app's host pipeline; the [n, 2*window] mask form emits byte-identical
    pairs in the same order — ascending j per center — at numpy speed)."""
    n = len(sent)
    if n < 2:
        return (np.empty(0, dtype=np.int64),) * 2
    b = rng.integers(1, window + 1, size=n)
    offs = np.concatenate([np.arange(-window, 0), np.arange(1, window + 1)])
    i = np.arange(n)
    J = i[:, None] + offs[None, :]                       # [n, 2W]
    valid = (np.abs(offs)[None, :] <= b[:, None]) & (J >= 0) & (J < n)
    centers = sent[np.broadcast_to(i[:, None], J.shape)[valid]]
    contexts = sent[J[valid]]
    return (centers.astype(np.int64), contexts.astype(np.int64))
