"""adapm-tpu: a TPU-native adaptive parameter manager.

Capabilities of alexrenz/AdaPM (key→dense-vector store with intent-driven
relocation/replication and managed sampling), re-designed for JAX/XLA/Pallas
over TPU device meshes. See ARCHITECTURE.md and SURVEY.md.
"""
from .base import CLOCK_MAX, LOCAL, WORKER_FINISHED, MgmtTechniques  # noqa
from .config import SystemOptions  # noqa
from .core.kv import Server, Worker  # noqa
from .parallel.mesh import MeshContext, get_mesh_context, make_mesh  # noqa

__version__ = "0.1.0"


def setup(num_keys: int, value_lengths, opts=None, num_shards=None,
          num_workers=None):
    """Convenience: build a mesh + Server (reference `ps::Setup` +
    `ServerT server(...)`, apps/simple.cc:107-133). Under the launcher
    (ADAPM_COORDINATOR set), this also joins the multi-process runtime —
    the reference's Postoffice::Start + scheduler rendezvous."""
    from .parallel import control
    control.init_from_env()
    ctx = make_mesh(num_shards)
    return Server(num_keys, value_lengths, opts=opts, ctx=ctx,
                  num_workers=num_workers)
