"""Headline benchmark: KGE ComplEx training throughput (triples/sec)
through the PARAMETER MANAGER — not the bare kernel.

The reference's headline workload is ComplEx KGE training (README.md:140-159;
BASELINE.json north star: beat AdaPM-CPU 8-node wall-clock). The timed loop
runs the full PM step the apps run: skewed (power-law) key batches, intent
signaling for the next batch, a planner round (`sync.run_round`) every step,
and the fused gather -> ComplEx score/grad -> AdaGrad -> scatter-add program
on the sharded HBM pools (ops/fused.py, device-routed).

A single chip is one shard, so every key is local in the timed loop — the
best case adaptive management aims for. The adaptive machinery itself
(replication, relocation, delta sync) is exercised in a separate 8-virtual-
shard phase whose stats (replicas_created, keys_synced, relocations > 0) are
reported in the same JSON line, plus a word2vec SGNS step benchmark and the
key-dedup lever measurement (docs/PERF.md "Levers").

vs_baseline: the reference publishes no in-tree numbers and its binary
cannot be built in this image (ZMQ/Boost/Eigen absent, installs forbidden —
BASELINE.md "Measured baselines"). The baseline is therefore MEASURED on
this host: a strong batched torch-CPU implementation of the same step,
per-core, scaled x64 for the paper's 8 nodes x 8 worker threads.
vs_baseline = tpu_triples_per_sec / (64 * torch_cpu_per_core_triples_per_sec).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "pm",
"w2v_pairs_per_sec", "dedup", ...}. The driver ALWAYS emits that line
(even on a crash) and exits nonzero naming any failed phase — an
artifact with dead phases must never be mistaken for a healthy run
(ISSUE 18 satellite).

Wedge-proofing (round 5): the driver process never imports jax. Every phase
runs in a subprocess with a hard timeout (`--phase NAME` re-entry), and the
backend is probed first. A wedged TPU relay (observed rounds 4-5:
`jax.devices()` hangs forever) therefore degrades the artifact — the probe
times out, device phases rerun with JAX_PLATFORMS=cpu, and the JSON line
carries `"tpu_unavailable": true` — instead of killing the whole benchmark
with rc=1 and losing the round's evidence.
"""
from __future__ import annotations

import json
import os
import subprocess
import time

# the adaptive phase runs on 8 virtual CPU shards in the same process;
# must be set before jax initializes its backends. The collective
# watchdog flags are probed first: a jaxlib that does not know them
# ABORTS the process on client init (xla_compat.py).
from xla_compat import mesh_flags  # noqa: E402

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = " ".join([_flags, mesh_flags(8)]).strip()

import sys

import numpy as np


def _progress(msg: str) -> None:
    """Phase progress on stderr (stdout carries only the JSON line)."""
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _skewed_keys(rng, n, size):
    """Power-law key skew (embedding workloads are zipfian): a realistic
    mix of hot and cold rows for the gather/scatter."""
    return (n * rng.random(size) ** 3).astype(np.int64).clip(0, n - 1)


def bench_tpu(E=200_000, R=1_000, d=128, B=4096, N=32, steps=50,
              warmup=5, dedup_batches=False, scan_steps=1,
              prefetch=False):
    """Returns (triples/sec, server) — the caller reads PM stats.

    scan_steps > 1 uses the K-step lax.scan window (runner.run_scan): one
    dispatch trains K steps, with intents signaled a window ahead and the
    K planner rounds driven while the device chews the window — the same
    PM work per step, dispatch overhead amortized K-fold.

    prefetch=True runs the SAME per-step loop through the intent-driven
    prefetch pipeline (SystemOptions.prefetch; core/intent.py): key
    batches pre-staged on device at intent time, the per-step planner
    round delegated to the pipeline's background thread so it overlaps
    the in-flight step, and device table mirrors re-staged by the
    pipeline after topology changes. The other phases pass
    prefetch=False explicitly so per-step/scan numbers keep measuring
    the inline baseline."""
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.models import make_kge_loss
    from adapm_tpu.ops import DeviceRoutedRunner

    num_keys = E + R
    _progress(f"kge phase: building server ({num_keys} keys)")
    # ADAPM_TRACE_SPANS=1: emit a Chrome trace-event JSON of the timed
    # loop (Perfetto-loadable; docs/OBSERVABILITY.md) — the bench twin
    # of the apps' --sys.trace.spans flag
    srv = adapm_tpu.setup(num_keys, 4 * d,
                          opts=SystemOptions(
                              cache_slots_per_shard=1,
                              sync_max_per_sec=0, prefetch=prefetch,
                              trace_spans=bool(
                                  os.environ.get("ADAPM_TRACE_SPANS"))))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    # initialize in slabs to bound host memory
    slab = 50_000
    for lo in range(0, num_keys, slab):
        hi = min(lo + slab, num_keys)
        vals = rng.normal(size=(hi - lo, 4 * d)).astype(np.float32) * 0.1
        vals[:, 2 * d:] = 1e-6
        w.set(np.arange(lo, hi), vals)
    srv.block()
    _progress("kge phase: init done, compiling + warmup")

    # device-routed runner: routing tables mirrored in HBM, negatives drawn
    # in-program (Local sampling scheme on device) — the host ships only the
    # positive triple keys per step
    runner = DeviceRoutedRunner(
        srv, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={k: 2 * d for k in ("s", "r", "o", "neg")},
        neg_role="neg", neg_shape=(B, N),
        neg_population=np.arange(E))

    def batch():
        b = {
            "s": _skewed_keys(rng, E, B),
            "r": rng.integers(E, E + R, B).astype(np.int64),
            "o": _skewed_keys(rng, E, B),
        }
        if dedup_batches:
            # dedup-lever upper bound: all-unique keys per role (what a
            # perfect in-step dedup would achieve for gather/scatter rows)
            for k in ("s", "o"):
                b[k] = rng.permutation(E)[:B].astype(np.int64)
        return b

    if scan_steps > 1:
        nwin = 2
        windows = [[batch() for _ in range(scan_steps)]
                   for _ in range(nwin)]
        win_intents = [np.unique(np.concatenate(
            [np.concatenate([b["s"], b["r"], b["o"]]) for b in win]))
            for win in windows]

        def pm_step(i):
            # intents one WINDOW ahead (the apps' lookahead contract),
            # one scan dispatch for K steps, then the K planner rounds +
            # clock ticks run while the device works through the window
            nxt = (i + 1) % nwin
            w.intent(win_intents[nxt], w.current_clock + 1,
                     w.current_clock + 1 + scan_steps)
            losses = runner.run_scan(windows[i % nwin], None, 0.1)
            for _ in range(scan_steps):
                srv.sync.run_round()
                w.advance_clock()
            return losses
    else:
        batches = [batch() for _ in range(4)]
        intent_keys = [np.unique(np.concatenate([b["s"], b["r"], b["o"]]))
                       for b in batches]
        # prefetch mode: batch key uploads staged ahead of dispatch
        # (the app loops stage at prepare() time; the rotating bench
        # batches stage once)
        staged = [runner.prefetch_keys(b) for b in batches] \
            if prefetch else None

        def pm_step(i):
            # the full app-step shape: intent for the NEXT batch, fused
            # step, one planner round, clock tick. With prefetch the
            # round rides the pipeline's background thread (drive_rounds)
            # and overlaps the step instead of serializing after it.
            nxt = (i + 1) % len(batches)
            w.intent(intent_keys[nxt], w.current_clock + 1,
                     w.current_clock + 2)
            if staged is not None:
                loss = runner(batches[i % len(batches)], None, 0.1,
                              staged=staged[i % len(batches)])
                srv.drive_rounds()
            else:
                loss = runner(batches[i % len(batches)], None, 0.1)
                srv.sync.run_round()
            w.advance_clock()
            return loss

    # Slope timing: some remote-attached TPU runtimes acknowledge
    # block_until_ready before work completes; only a value fetch truly
    # syncs, at a large fixed RTT. Timing two loop lengths and taking the
    # slope removes both the RTT and any warmup from the estimate.
    assert steps >= 4, "slope timing needs steps >= 4 (two loop lengths)"

    def timed(n: int) -> float:
        t0 = time.perf_counter()
        loss = None
        for i in range(n):
            loss = pm_step(i)
        # force completion of the whole donated chain (scan returns [K])
        float(np.asarray(loss).ravel()[-1])
        return time.perf_counter() - t0

    for _ in range(warmup):
        pm_step(0)
    if prefetch and srv.prefetch is not None:
        # settle before timing: the pipeline's background rounds change
        # placement (and flip the runner between its compiled
        # with/without-replica variants) asynchronously — if that compile
        # lands INSIDE the short timing loop, slope timing subtracts it
        # from the long loop and fabricates absurd throughput (observed
        # 17k triples/s on a 3k box). Flush the backlog, step once to
        # compile whichever variant the settled topology selects, flush
        # again — then both phases measure the same settled steady state.
        srv.prefetch.flush()
        for _ in range(2):
            pm_step(0)
        srv.prefetch.flush()
    timed(1)
    _progress("kge phase: timing")
    t_short = timed(steps // 4)
    t_long = timed(steps)
    dt = (t_long - t_short) / (steps - steps // 4)
    per_disp = B * scan_steps
    _progress(f"kge phase: {per_disp / dt:.0f} triples/s "
              f"({dt * 1e3:.1f} ms/dispatch, scan_steps={scan_steps})")
    return per_disp / dt, srv


def bench_adaptive_pm(E=20_000, d=32, B=1024, N=8, steps=30):
    """Adaptive-management phase on an 8-virtual-shard CPU mesh: two
    workers with overlapping skewed intents force replication, exclusive
    tails force relocation, and per-step planner rounds ship deltas —
    the machinery a multi-chip mesh exercises per step. Returns the sync
    stats dict recorded for BENCH_r03."""
    import jax

    from adapm_tpu import Server
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.models import make_kge_loss
    from adapm_tpu.ops import FusedStepRunner
    from adapm_tpu.parallel.mesh import MeshContext, Mesh

    cpu = jax.devices("cpu")
    mesh = MeshContext(Mesh(np.asarray(cpu), ("kv",)))
    srv = Server(E + 64, 4 * d, ctx=mesh,
                 opts=SystemOptions(sync_max_per_sec=0,
                                    cache_slots_per_shard=4096))
    ws = [srv.make_worker(i) for i in range(2)]
    runner = FusedStepRunner(
        srv, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={k: 2 * d for k in ("s", "r", "o", "neg")})
    rng = np.random.default_rng(1)
    t0 = time.perf_counter()
    for i in range(steps):
        for wi, w in enumerate(ws):
            # hot head shared by both workers (-> replication), disjoint
            # cold tails per worker (-> relocation)
            hot = _skewed_keys(rng, 2_000, B // 2)
            cold = rng.integers(2_000 + wi * 9_000,
                                2_000 + (wi + 1) * 9_000, B // 2)
            s = np.concatenate([hot, cold])
            batch = {"s": s, "r": np.full(B, E + wi, np.int64),
                     "o": _skewed_keys(rng, E, B),
                     "neg": _skewed_keys(rng, E, B * N).reshape(B, N)}
            w.intent(np.unique(s), w.current_clock + 1,
                     w.current_clock + 3)
            runner(batch, None, 0.05, shard=w.shard)
            w.advance_clock()
        srv.sync.run_round(all_channels=(i % 4 == 0))
    srv.quiesce()
    dt = time.perf_counter() - t0
    s = srv.sync.stats
    out = {"replicas_created": s.replicas_created,
           "replicas_dropped": s.replicas_dropped,
           "relocations": s.relocations,
           "keys_synced": s.keys_synced,
           "intents_processed": s.intents_processed,
           "adaptive_steps_per_sec": round(2 * steps / dt, 1),
           "metrics": srv.metrics_snapshot()}
    srv.shutdown()
    return out


def bench_mgmt(replicas=50_000, vlen=16, rounds=40, trickle=512):
    """Management-plane microbench (ISSUE 3): planner rounds/sec and
    replica-staleness P50/P90 at ~`replicas` live replicas on a CPU
    mesh. One worker holds never-expiring intent on keys owned by other
    shards (REPLICATION_ONLY pins the decision); between rounds a
    `trickle`-key push batch lands (~1% of the table — the realistic
    shape the dirty filter exists for: most replicas idle, a small hot
    set written), and ONLY the `run_round` calls are timed, so the
    number is the planner's cost, not the workload generator's.
    docs/PERF.md "Management-plane scaling" records before/after
    numbers for this host."""
    import jax

    from adapm_tpu import Server
    from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.obs.metrics import hist_percentile
    from adapm_tpu.parallel.mesh import Mesh, MeshContext

    cpu = jax.devices("cpu")
    mesh = MeshContext(Mesh(np.asarray(cpu), ("kv",)))
    S = mesh.num_shards
    assert S >= 2, "mgmt phase needs >= 2 virtual shards"
    num_keys = int(replicas * S / (S - 1)) + 512
    srv = Server(num_keys, vlen, ctx=mesh,
                 opts=SystemOptions(
                     techniques=MgmtTechniques.REPLICATION_ONLY,
                     sync_max_per_sec=0, prefetch=False,
                     cache_slots_per_shard=replicas + 1024))
    w = srv.make_worker(1)
    keys = np.arange(num_keys)
    cand = keys[srv.ab.owner[keys] != w.shard][:replicas]
    _progress(f"mgmt phase: replicating {replicas} keys onto shard "
              f"{w.shard} ({S} shards)")
    w.intent(cand, 0, CLOCK_MAX)
    srv.sync.run_round(force_intents=True, all_channels=True)
    live = int(sum(len(t) for t in srv.sync.replicas))
    rng = np.random.default_rng(0)

    def trickle_push():
        hot = rng.choice(cand, trickle, replace=False)
        w.push(hot, np.ones((trickle, vlen), np.float32))

    # warmup compiles every channel's sync-program bucket shape
    for _ in range(2 * srv.sync.num_channels):
        trickle_push()
        srv.sync.run_round()
        w.advance_clock()
    srv.block()
    _progress("mgmt phase: timing")
    dt = 0.0
    for _ in range(rounds):
        trickle_push()
        t0 = time.perf_counter()
        srv.sync.run_round()
        dt += time.perf_counter() - t0
        w.advance_clock()
    srv.block()
    stale = srv.sync._h_staleness.snap()
    st = srv.sync.stats
    out = {"replicas_live": live,
           "rounds_per_sec": round(rounds / dt, 2),
           "round_ms": round(dt / rounds * 1e3, 2),
           "staleness_p50_clocks": round(hist_percentile(stale, 0.50), 2),
           "staleness_p90_clocks": round(hist_percentile(stale, 0.90), 2),
           "keys_shipped": st.keys_synced,
           "keys_considered": st.keys_considered,
           "dirty_filter": bool(srv.opts.sync_dirty_only),
           "trickle_keys_per_round": trickle}
    srv.shutdown()
    return out


def bench_compress(replicas=20_000, vlen=16, rounds=16, trickle=512,
                   cold_E=20_000, cold_L=32, cold_hot_rows=256,
                   drift_steps=12):
    """Compression-plane microbench (ISSUE 8): the three numbers the
    acceptance bar names, measured on this host.

    (1) Sync bytes/round on the mgmt-phase workload (REPLICATION_ONLY,
    ~1%/round trickle pushes, dirty filter on) for each
    --sys.sync.compress mode — the per-round wire bytes the shipped
    delta rows cost, read from the store accounting the
    sync.bytes_per_round gauge uses. The rng is seeded identically per
    mode, so the dirty population matches and the ratio vs the "off"
    run isolates the wire format (fp16 target <= 0.55x, int8 <= 0.30x).

    (2) Cold-store host bytes/row per --sys.tier.cold_dtype, via
    TierManager.cold_bytes_per_row() — dense store + scale column +
    parked EF residuals, the honest number (fp16 target ~0.5x fp32).

    (3) The drift curve: a push/promote/demote/sync storm on a
    quantized+compressed server vs an untiered fp32 shadow, max-abs
    read error recorded per step — bounded by the docs/MEMORY.md
    contract, flat-not-growing is the EF loop working (the same storm
    scripts/compress_drift_check.py guards in CI)."""
    import jax

    from adapm_tpu import Server
    from adapm_tpu.base import CLOCK_MAX, MgmtTechniques
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.parallel.mesh import Mesh, MeshContext

    def mk_mesh():
        return MeshContext(Mesh(np.asarray(jax.devices("cpu")), ("kv",)))

    S = mk_mesh().num_shards
    assert S >= 2, "compress phase needs >= 2 virtual shards"
    num_keys = int(replicas * S / (S - 1)) + 512

    def sync_bytes_for(mode: str) -> dict:
        srv = Server(num_keys, vlen, ctx=mk_mesh(),
                     opts=SystemOptions(
                         techniques=MgmtTechniques.REPLICATION_ONLY,
                         sync_max_per_sec=0, prefetch=False,
                         sync_compress=mode,
                         cache_slots_per_shard=replicas + 1024))
        w = srv.make_worker(1)
        keys = np.arange(num_keys)
        cand = keys[srv.ab.owner[keys] != w.shard][:replicas]
        w.intent(cand, 0, CLOCK_MAX)
        srv.sync.run_round(force_intents=True, all_channels=True)
        rng = np.random.default_rng(0)
        b0 = sum(st.sync_bytes_shipped for st in srv.stores)
        f0 = sum(st.sync_bytes_full for st in srv.stores)
        for _ in range(rounds):
            hot = rng.choice(cand, trickle, replace=False)
            w.push(hot, np.ones((trickle, vlen), np.float32))
            srv.sync.run_round()
            w.advance_clock()
        srv.block()
        shipped = sum(st.sync_bytes_shipped for st in srv.stores) - b0
        full = sum(st.sync_bytes_full for st in srv.stores) - f0
        resid = max(st.ef_residual_norm() for st in srv.stores)
        srv.shutdown()
        return {"bytes_per_round": round(shipped / rounds),
                "full_equiv_per_round": round(full / rounds),
                "ef_residual_norm": resid}

    _progress("compress phase: sync bytes/round per mode")
    sync_out = {m: sync_bytes_for(m) for m in ("off", "fp16", "int8")}
    raw = sync_out["off"]["bytes_per_round"]
    sync_ratios = {m: (round(sync_out[m]["bytes_per_round"] / raw, 4)
                       if raw else None) for m in ("fp16", "int8")}

    def cold_bytes_for(mode: str) -> tuple:
        srv = Server(cold_E, cold_L, ctx=mk_mesh(),
                     opts=SystemOptions(
                         sync_max_per_sec=0, prefetch=False, tier=True,
                         tier_hot_rows=cold_hot_rows,
                         tier_cold_dtype=mode))
        w = srv.make_worker(0)
        rng = np.random.default_rng(1)
        # off-grid values so quantized modes pay their worst-case
        # residual population (the honest bytes/row, not the zeros)
        w.set(np.arange(cold_E),
              rng.normal(size=(cold_E, cold_L)).astype(np.float32)
              * np.pi)
        srv.block()
        bpr = srv.tier.cold_bytes_per_row()
        # dense at-rest bytes only (stored rows + scale column): what
        # the format costs per row once the CAP-BOUNDED residual map
        # amortizes away at beyond-HBM row counts — exactly 0.5x (fp16)
        # / ~0.26x (int8) of fp32
        dense = sum(st.coldq.q.nbytes
                    + (st.coldq.scale.nbytes if st.coldq.scale
                       is not None else 0) for st in srv.stores)
        rows = sum(st.coldq.num_shards * st.coldq.main_slots
                   for st in srv.stores)
        srv.shutdown()
        return round(bpr, 3), round(dense / rows, 3)

    _progress("compress phase: cold-store bytes/row per dtype")
    cold_raw = {m: cold_bytes_for(m) for m in ("fp32", "fp16", "int8")}
    # "with_resid" is the honest host cost at THIS phase's row count
    # (the bounded residual map is a fixed overhead, large relative to
    # a small bench table, vanishing at the scale tiering exists for);
    # "dense" is the at-rest format itself
    cold_out = {m: {"with_resid": cold_raw[m][0], "dense": cold_raw[m][1]}
                for m in cold_raw}
    cold_ratios = {m: {"with_resid": round(
                           cold_raw[m][0] / cold_raw["fp32"][0], 4),
                       "dense": round(
                           cold_raw[m][1] / cold_raw["fp32"][1], 4)}
                   for m in ("fp16", "int8")}

    def drift_curve(mode: str) -> dict:
        E, L = 384, 8
        srv = Server(E, L, ctx=mk_mesh(),
                     opts=SystemOptions(
                         sync_max_per_sec=0, prefetch=False, tier=True,
                         tier_hot_rows=16, tier_cold_dtype=mode,
                         sync_compress=mode))
        ref = Server(E, L, ctx=mk_mesh(),
                     opts=SystemOptions(sync_max_per_sec=0,
                                        prefetch=False))
        w, wr = srv.make_worker(0), ref.make_worker(0)
        rng = np.random.default_rng(2)
        vals = rng.normal(size=(E, L)).astype(np.float32)
        w.set(np.arange(E), vals)
        wr.set(np.arange(E), vals)
        keys = np.arange(E)
        # long-lived replicas of non-local keys so the compressed sync
        # rounds actually ship deltas (not just the tier churn)
        repl = keys[srv.ab.owner[keys] != w.shard][:48]
        for ww, ss in ((w, srv), (wr, ref)):
            ww.intent(repl, 0, CLOCK_MAX)
            ss.sync.run_round(force_intents=True, all_channels=True)
        curve = []
        for _ in range(drift_steps):
            ks = np.concatenate([rng.integers(0, E, 16),
                                 rng.choice(repl, 8, replace=False)])
            v = rng.normal(size=(24, L)).astype(np.float32)
            w.push(ks, v)
            wr.push(ks, v)
            srv.tier.promote_keys(rng.choice(E, 32, replace=False))
            srv.tier.demote_keys(rng.choice(E, 32, replace=False))
            srv.tier.maintain()
            srv.sync.run_round(force_intents=True, all_channels=True)
            ref.sync.run_round(force_intents=True, all_channels=True)
            a = np.asarray(srv.read_main(keys)).reshape(E, L)
            b = np.asarray(ref.read_main(keys)).reshape(E, L)
            curve.append(round(float(np.abs(a - b).max()), 6))
        # contract bound: two grid steps of the row's max-abs
        from adapm_tpu.tier.quant import grid_step
        bound = round(float((2.0 * grid_step(mode, b)).max() + 1e-6), 6)
        srv.shutdown()
        ref.shutdown()
        return {"max_abs_drift_per_step": curve, "final": curve[-1],
                "contract_bound": bound,
                "within_contract": curve[-1] <= bound}

    _progress("compress phase: drift curves")
    drift = {m: drift_curve(m) for m in ("fp16", "int8")}

    return {"sync": sync_out,
            "sync_bytes_ratio_vs_fp32": sync_ratios,
            "cold_bytes_per_row": cold_out,
            "cold_bytes_ratio_vs_fp32": cold_ratios,
            "drift": drift,
            "trickle_keys_per_round": trickle,
            "value_length_sync": vlen, "value_length_cold": cold_L}


def bench_serve(E=20_000, vlen=32, clients=32, lookups_per_client=40,
                B=64):
    """Online-serving phase (ISSUE 4): closed-loop load generator — N
    client threads each issuing `lookups_per_client` coalesced
    `ServeSession.lookup` calls of B skewed keys — against the
    sequential per-request `Worker.pull_sync` baseline (one request at
    a time, the pre-serve API). Reports QPS for both, the coalescing
    gain, P50/P99 lookup latency (serve.latency_s via hist_percentile),
    micro-batch shape, and a deadline-overload segment that must SHED
    (serve.shed_total > 0) instead of hanging."""
    import threading

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.obs.metrics import hist_percentile
    from adapm_tpu.serve import (DeadlineExceededError, ServeOverloadError,
                                 ServePlane)

    _progress(f"serve phase: building server ({E} keys, {clients} clients)")
    srv = adapm_tpu.setup(E, vlen,
                          opts=SystemOptions(sync_max_per_sec=0,
                                             prefetch=False))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    slab = 50_000
    for lo in range(0, E, slab):
        hi = min(lo + slab, E)
        w.set(np.arange(lo, hi),
              rng.normal(size=(hi - lo, vlen)).astype(np.float32))
    srv.block()
    total = clients * lookups_per_client
    batches = [[_skewed_keys(rng, E, B) for _ in range(lookups_per_client)]
               for _ in range(clients)]

    # sequential per-request baseline: same total request count, one
    # pull_sync at a time (warm the gather bucket shape first)
    w.pull_sync(batches[0][0])
    _progress("serve phase: sequential baseline")
    t0 = time.perf_counter()
    for cb in batches:
        for b in cb:
            w.pull_sync(b)
    t_seq = time.perf_counter() - t0
    seq_qps = total / t_seq

    plane = ServePlane(srv)
    sess0 = plane.session()
    sess0.lookup(batches[0][0])  # warm the coalesced path + compiles
    lat0 = srv.obs.find("serve.latency_s").snap()["count"]
    barrier = threading.Barrier(clients + 1)
    errs: list = []

    def client(ci):
        try:
            sess = plane.session()
            barrier.wait()
            for b in batches[ci]:
                sess.lookup(b)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(clients)]
    for t in threads:
        t.start()
    _progress("serve phase: closed-loop coalesced load")
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    t_coal = time.perf_counter() - t0
    assert not errs, errs[:3]
    qps = total / t_coal

    lat = srv.obs.find("serve.latency_s").snap()
    bsz = srv.obs.find("serve.batch_size").snap()
    # overload segment: deadlines shorter than the micro-batch queue
    # wait under a request burst -> requests are shed loudly, never
    # parked (the acceptance contract). A 0.001 ms deadline is expired
    # by take time, so sheds are deterministic.
    shed_before = srv.obs.find("serve.shed_total").value
    for _ in range(64):
        try:
            sess0.lookup(batches[0][0], deadline_ms=0.001)
        except (DeadlineExceededError, ServeOverloadError):
            pass
    shed = srv.obs.find("serve.shed_total").value - shed_before

    # -- SLO autopilot + per-request breakdown exemplar (ISSUE 7) -----
    # Rebuild the plane with flight tracing attached, an SLO target,
    # and a deliberately oversized micro-batch window (4x the target):
    # the artifact then carries the controller's convergence
    # (wait_us_adjustments, achieved P99 vs target) and one sampled
    # request's queue/batch/dispatch/device split — where the
    # milliseconds actually went, not just totals.
    _progress("serve phase: slo autopilot segment")
    plane.close()
    from adapm_tpu.obs.flight import FlightTracer
    srv.flight = FlightTracer(registry=srv.obs, rank=srv.pid)
    slo_target_ms = 20.0
    srv.opts.serve_slo_ms = slo_target_ms
    srv.opts.serve_max_wait_us = int(slo_target_ms * 4e3)
    plane2 = ServePlane(srv)
    h_lat = srv.obs.find("serve.latency_s")
    stop = threading.Event()
    errs2: list = []

    def slo_client(ci):
        try:
            sess = plane2.session()
            crng = np.random.default_rng(1000 + ci)
            while not stop.is_set():
                sess.lookup(_skewed_keys(crng, E, B))
        except BaseException as e:  # noqa: BLE001
            errs2.append(e)

    slo_threads = [threading.Thread(target=slo_client, args=(ci,))
                   for ci in range(8)]
    for t in slo_threads:
        t.start()
    time.sleep(1.5)             # controller walks the window down
    lat_a = h_lat.snap()        # trailing window: post-convergence P99
    time.sleep(1.5)
    lat_b = h_lat.snap()
    stop.set()
    for t in slo_threads:
        t.join(timeout=60)
    assert not errs2, errs2[:3]
    win = {"count": lat_b["count"] - lat_a["count"],
           "bounds": lat_b["bounds"],
           "buckets": [a - b for a, b in zip(lat_b["buckets"],
                                             lat_a["buckets"])]}
    achieved_p99_ms = round(1e3 * hist_percentile(win, 0.99), 3)
    slo_rep = plane2.slo.report()
    exemplar = srv.flight.exemplar()
    # snapshot while the plane is live: serve.readiness and the slo
    # section are filled from the open plane, close() empties them
    snap = srv.metrics_snapshot()
    plane2.close()

    # -- mixed-tenant open-loop segment (ISSUE 9): 2 tenants at skewed
    # priorities + the read-only replica fast path, under CONCURRENT
    # training pushes. gold (priority 2) paces a fixed arrival rate on
    # a hot working set the snapshot covers; bronze (priority 0)
    # floods uniformly with a short deadline; one pusher hammers
    # disjoint keys through the server lock the whole time. The
    # artifact carries per-tenant qps/P99/shed and replica_hit_rate
    # next to the closed-loop numbers above.
    _progress("serve phase: mixed-tenant open-loop segment")
    # keep the flight tracer ATTACHED through this segment (ISSUE 15
    # satellite): the pusher + serve load below are exactly what the
    # r12 freshness probe measures — push wall time -> first servable
    # read — and the artifact finally surfaces flight.freshness_s
    # P50/P99 instead of dropping the probe on the floor
    srv.opts.serve_slo_ms = 0.0
    srv.opts.serve_max_wait_us = 200   # undo the SLO segment's 4x window
    srv.opts.serve_dispatchers = 2
    srv.opts.serve_replica_rows = 1024
    srv.opts.serve_replica_refresh_ms = 10.0
    plane3 = ServePlane(srv)
    plane3.configure_tenant("gold", priority=2)
    plane3.configure_tenant("bronze", priority=0)
    hot = np.arange(512, dtype=np.int64)
    warm_sess = plane3.session(tenant="gold")
    warm_sess.lookup(hot)   # score the whole working set
    plane3.replica.refresh_now()
    h0r = srv.obs.find("serve.replica_hits_total").value
    b0r = srv.obs.find("serve.batches_total").value
    stop3 = threading.Event()
    errs3: list = []
    gold_lat: list = []
    bronze_done = [0, 0]        # served, shed/rejected (client-side)
    t_seg = 2.5

    def t_pusher():
        prng = np.random.default_rng(60)
        ks_all = np.arange(2048, E, dtype=np.int64)
        try:
            while not stop3.is_set():
                ks = np.unique(prng.choice(ks_all, 128))
                w.push(ks, np.ones((len(ks), vlen), np.float32))
        except BaseException as e:  # noqa: BLE001
            errs3.append(e)

    def t_gold():
        prng = np.random.default_rng(61)
        sess = plane3.session(tenant="gold")
        try:
            while not stop3.is_set():
                t0g = time.perf_counter()
                try:
                    sess.lookup(prng.choice(hot, B), deadline_ms=1000.0)
                    gold_lat.append(time.perf_counter() - t0g)
                except (DeadlineExceededError, ServeOverloadError):
                    pass
                time.sleep(0.008)   # the paced open-loop arrival rate
        except BaseException as e:  # noqa: BLE001
            errs3.append(e)

    def t_bronze(ci):
        prng = np.random.default_rng(62 + ci)
        sess = plane3.session(tenant="bronze")
        try:
            while not stop3.is_set():
                try:
                    sess.lookup(prng.integers(0, E, B), deadline_ms=10.0)
                    bronze_done[0] += 1
                except (DeadlineExceededError, ServeOverloadError):
                    bronze_done[1] += 1
        except BaseException as e:  # noqa: BLE001
            errs3.append(e)

    t3 = [threading.Thread(target=t_pusher),
          threading.Thread(target=t_gold)] + \
         [threading.Thread(target=t_bronze, args=(ci,))
          for ci in range(4)]
    for t in t3:
        t.start()
    time.sleep(t_seg)
    stop3.set()
    for t in t3:
        t.join(timeout=60)
    assert not errs3, errs3[:3]
    gold_lat.sort()
    gold_ten = plane3.queue.tenant("gold")
    bronze_ten = plane3.queue.tenant("bronze")
    hits_d = srv.obs.find("serve.replica_hits_total").value - h0r
    batches_d = srv.obs.find("serve.batches_total").value - b0r
    tenant_out = {
        "seconds": t_seg,
        # segment-windowed (the serve.replica_hit_rate gauge is
        # cumulative over the server's life and would be diluted by
        # the closed-loop phases above)
        "replica_hit_rate": round(hits_d / max(1.0, batches_d), 4),
        "gold": {
            "priority": 2,
            "qps": round(len(gold_lat) / t_seg, 1),
            "p50_ms": round(1e3 * gold_lat[len(gold_lat) // 2], 3)
            if gold_lat else None,
            "p99_ms": round(
                1e3 * gold_lat[max(0, int(0.99 * len(gold_lat)) - 1)],
                3) if gold_lat else None,
            "served": int(gold_ten.c_served.value),
            "shed": int(gold_ten.c_shed.value +
                        gold_ten.c_rejected.value)},
        "bronze": {
            "priority": 0,
            "qps": round(bronze_done[0] / t_seg, 1),
            "served": int(bronze_ten.c_served.value),
            "shed": int(bronze_ten.c_shed.value +
                        bronze_ten.c_rejected.value)}}
    # event-to-servable freshness (ISSUE 15 satellite; the r12 probe
    # was never surfaced in the artifact): P50/P99 of
    # flight.freshness_s over the tenant segment's concurrent
    # push/serve traffic, via the same hist_percentile extraction the
    # latency numbers use
    h_fresh = srv.obs.find("flight.freshness_s")
    fresh_snap = h_fresh.snap() if h_fresh is not None else None
    freshness_out = {
        "samples": int(fresh_snap["count"]) if fresh_snap else 0,
        "p50_ms": round(1e3 * hist_percentile(fresh_snap, 0.50), 3)
        if fresh_snap and fresh_snap["count"] else None,
        "p99_ms": round(1e3 * hist_percentile(fresh_snap, 0.99), 3)
        if fresh_snap and fresh_snap["count"] else None,
        "evicted": int(srv.flight.freshness.evicted)
        if srv.flight is not None else 0}
    srv.flight = None   # detach before shutdown: no stray export
    plane3.close()
    _progress(f"serve phase: freshness p50 {freshness_out['p50_ms']} "
              f"ms / p99 {freshness_out['p99_ms']} ms over "
              f"{freshness_out['samples']} samples")
    _progress(f"serve phase: mixed tenants — gold "
              f"{tenant_out['gold']['qps']} qps p99 "
              f"{tenant_out['gold']['p99_ms']} ms / bronze "
              f"{tenant_out['bronze']['qps']} qps "
              f"{tenant_out['bronze']['shed']} shed; replica_hit_rate "
              f"{tenant_out['replica_hit_rate']}")
    _progress(f"serve phase: {qps:.0f} qps coalesced vs {seq_qps:.0f} "
              f"sequential, {shed} shed under overload; slo p99 "
              f"{achieved_p99_ms:.1f} ms vs {slo_target_ms:.0f} ms "
              f"target in {slo_rep['adjustments']} adjustments")
    out = {"clients": clients,
           "lookups": total,
           "keys_per_lookup": B,
           "qps": round(qps, 1),
           "sequential_qps": round(seq_qps, 1),
           "coalesce_gain": round(qps / seq_qps - 1.0, 3),
           "latency_p50_ms": round(1e3 * hist_percentile(lat, 0.50), 3),
           "latency_p99_ms": round(1e3 * hist_percentile(lat, 0.99), 3),
           "timed_lookups_in_hist": lat["count"] - lat0,
           "batch_size_avg": round(bsz["avg"], 2),
           "batch_size_max": bsz["max"],
           "shed_total_overload": int(shed),
           # the SLO autopilot's convergence record (obs/slo.py) — the
           # windowed P99 AFTER the controller settled vs the target,
           # and every knob move it took to get there
           "slo": {"target_ms": slo_target_ms,
                   "achieved_p99_ms": achieved_p99_ms,
                   "wait_us_adjustments": slo_rep["adjustments"],
                   "initial_wait_us": int(slo_target_ms * 4e3),
                   "final_wait_us": slo_rep["wait_us"],
                   "recent_adjustments": slo_rep["recent_adjustments"]},
           # one sampled request's queue/batch/dispatch/device split
           # (ms) — where a lookup's time went (obs/flight.py)
           "flight_exemplar": exemplar,
           # the mixed-tenant open-loop segment (ISSUE 9): per-tenant
           # qps/P99/shed under concurrent training pushes, and the
           # fraction of batches the read-only replica served lock-free
           "tenants": tenant_out,
           # event-to-servable staleness over the tenant segment
           # (ISSUE 15 satellite; flight.freshness_s, obs/flight.py)
           "freshness": freshness_out,
           "metrics": snap}
    # the tracer was detached after the freshness extraction above; a
    # shutdown export would otherwise drop a flight.<rank>.trace.json
    # into the working directory
    srv.shutdown()
    return out


def bench_bag(E=200_000, L=128, nbags=256, members_per_bag=32, rounds=30,
              tables=2):
    """Fused embedding-bag read phase (ISSUE 16): the DLRM/Criteo read
    shape — each request asks for `nbags` POOLED bags (sum over
    `members_per_bag` member rows each, split across `tables` feature
    tables of one length class) — timed three ways over the SAME bag
    workload:

      fused       ServeSession.lookup_bags with the fused gather+pool
                  device program (one segment-sum gather per length
                  class, pooled rows on the wire);
      hostpool    the same lookup_bags calls with --sys.serve.bags off:
                  the batcher gathers the member union flat and pools
                  on the host (the bit-identity reference path);
      sequential  the pre-bag API: one plain `lookup` per table, pooled
                  by the caller — what a client had to do before
                  serve/bags.py existed.

    All three must return bit-identical pooled rows (asserted on the
    first round). The artifact carries qps + P50/P99 per variant, the
    fused/hostpool median ratio (scripts/portdiff_check.py gates it —
    < 0.9 on accelerator backends, where the fused program's wire-byte
    saving (nbags*L pooled rows vs n*L member rows) is real transfer;
    a host-CPU multiplex memcpy can't see that saving, so CPU runs
    report near-parity and the guard relaxes accordingly), the
    serve.bag_* counters, and a measured kernel cost table calibrated
    on the live server (ops/costs.py) including its fused-vs-host
    verdict at this workload's shape — the per-backend measurement
    that lets dispatch pick the cheaper path instead of guessing."""
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.ops.costs import calibrate_server
    from adapm_tpu.serve import ServePlane
    from adapm_tpu.serve.bags import pool_bags_host

    n_members = nbags * members_per_bag
    _progress(f"bag phase: building server ({E} keys x {L}, "
              f"{nbags} bags x {members_per_bag} members, "
              f"{tables} tables)")
    srv = adapm_tpu.setup(E, L,
                          opts=SystemOptions(sync_max_per_sec=0,
                                             prefetch=False))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    slab = 25_000
    for lo in range(0, E, slab):
        hi = min(lo + slab, E)
        w.set(np.arange(lo, hi),
              rng.normal(size=(hi - lo, L)).astype(np.float32))
    srv.block()

    # per-round bag workloads, split evenly across `tables` tables of
    # one length class (the fused path coalesces them into ONE
    # segment-sum gather; the sequential baseline pays one lookup per
    # table). Members are uniform over a LARGE vocab — the DLRM shape:
    # sparse-feature tables are huge, so a batch's members barely
    # dedup, which is exactly when pool-on-device pays (a tiny vocab
    # would let the host path shrink its gather via the union dedup)
    nb_t = nbags // tables
    mem_t = nb_t * members_per_bag
    bg_t = np.arange(0, mem_t + 1, members_per_bag)
    work = [[rng.integers(0, E, mem_t) for _ in range(tables)]
            for _ in range(rounds)]

    plane = ServePlane(srv)
    sess = plane.session()

    def run_bags(tks):
        return sess.lookup_bags(tks, [bg_t] * tables, pooling="sum")

    def run_sequential(tks):
        out = []
        for ks in tks:
            rows = sess.lookup(ks)
            out.append(pool_bags_host(rows,
                                      np.repeat(np.arange(nb_t),
                                                members_per_bag),
                                      nb_t, "sum"))
        return out

    def timed(fn):
        lats = []
        t0 = time.perf_counter()
        for tks in work:
            t1 = time.perf_counter()
            fn(tks)
            lats.append(time.perf_counter() - t1)
        wall = time.perf_counter() - t0
        lats.sort()
        return {"qps": round(rounds / wall, 1),
                "p50_ms": round(1e3 * lats[len(lats) // 2], 3),
                "p99_ms": round(
                    1e3 * lats[max(0, int(0.99 * len(lats)) - 1)], 3),
                "median_s": lats[len(lats) // 2]}

    # warm every path (gather bucket compiles) + the bit-identity check:
    # fused == host pool == caller pool, bitwise, on round 0
    ref_fused = run_bags(work[0])
    srv.opts.serve_bags = False
    ref_host = run_bags(work[0])
    srv.opts.serve_bags = True
    ref_seq = run_sequential(work[0])
    for a, b, c in zip(ref_fused, ref_host, ref_seq):
        assert np.array_equal(a, b), "fused != host pool (bitwise)"
        assert np.array_equal(a, c), "fused != sequential pool (bitwise)"

    _progress("bag phase: fused segment")
    fused = timed(run_bags)
    _progress("bag phase: hostpool segment")
    srv.opts.serve_bags = False
    hostpool = timed(run_bags)
    srv.opts.serve_bags = True
    _progress("bag phase: sequential segment")
    sequential = timed(run_sequential)

    snap = srv.metrics_snapshot()["serve"]
    bag_counters = {k: v for k, v in snap.items()
                    if k.startswith("bag_")}
    plane.close()

    # measured kernel cost table on the live server, calibrated at the
    # workload's padded member count next to a small bucket — the
    # dispatch verdict the batcher would consult with --sys.costs.table
    _progress("bag phase: calibrating cost table")
    costs = calibrate_server(srv, buckets=(512, n_members), repeats=3)
    verdict = costs.prefer_fused(L, n_members, "float32", "sum")
    ratio = round(fused["median_s"] / hostpool["median_s"], 3)
    for d in (fused, hostpool, sequential):
        del d["median_s"]
    _progress(f"bag phase: fused {fused['qps']} qps vs hostpool "
              f"{hostpool['qps']} vs sequential {sequential['qps']}; "
              f"median ratio {ratio}, cost-table verdict "
              f"prefer_fused={verdict}")
    out = {"bags_per_lookup": nbags,
           "members_per_bag": members_per_bag,
           "value_length": L,
           "tables": tables,
           "lookups": rounds,
           "fused": fused,
           "hostpool": hostpool,
           "sequential": sequential,
           # medians, fused/hostpool: < 1 means the fused program beats
           # gather-then-host-pool on this backend at this shape
           "fused_vs_hostpool": ratio,
           "seq_gain": round(sequential["p50_ms"] / fused["p50_ms"],
                             3),
           "bag_metrics": bag_counters,
           "cost_table": {"backend": costs.backend,
                          "entries": costs.entries(),
                          "prefer_fused_at_workload": verdict}}
    srv.shutdown()
    return out


def bench_replay(E=8_000, vlen=16, steps=120, skew=8.0):
    """Trace-replay phase (ISSUE 15): capture a zipf pull/push/serve
    workload once (--sys.trace.workload), then score a hot-capacity
    knob sweep OFFLINE by deterministic replay (adapm_tpu/replay) —
    the artifact carries the captured-trace shape, per-candidate
    hot-hit/serve scores, the ranked comparison, and the determinism
    digest (same seed + knobs => bit-identical reads, re-verified
    here with a second run of the winner). The capture run also
    records the decision plane (ISSUE 17, --sys.trace.decisions) and
    the artifact embeds the labeled-dataset summary — decisions per
    plane, attribution closure, regret counts — from the same
    workload."""
    import tempfile

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.replay import (ReplayEngine, export_dataset,
                                  load_dtrace, load_wtrace,
                                  per_shard_hot_rows, rank_candidates)
    from adapm_tpu.serve import ServePlane

    # the .wtrace only needs to live until load_wtrace parses it; the
    # context bounds the tempdir so no adapm_replay_* dir outlives the
    # phase (success or failure)
    with tempfile.TemporaryDirectory(prefix="adapm_replay_") as tmp:
        path = os.path.join(tmp, "bench.wtrace")
        dpath = os.path.join(tmp, "bench.dtrace")
        _progress(f"replay phase: capturing workload ({E} keys x "
                  f"{vlen}, {steps} steps)")
        # tier on for the CAPTURE run so the decision plane has real
        # promote/demote choices to record (replay re-decides
        # management from the op stream, and every candidate overrides
        # the tier knobs — the sweep is unaffected)
        opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                             tier=True,
                             tier_hot_rows=per_shard_hot_rows(E, 0.5),
                             trace_workload=path,
                             trace_workload_keys=512,
                             trace_decisions=dpath)
        srv = adapm_tpu.setup(E, vlen, opts=opts, num_workers=1)
        w = srv.make_worker(0)
        rng = np.random.default_rng(0)
        w.wait(w.set(np.arange(E), np.ones((E, vlen), np.float32)))
        plane = ServePlane(srv)
        sess = plane.session()
        t0 = time.perf_counter()
        for i in range(steps):
            ks = np.unique((E * rng.random(64) ** skew)
                           .astype(np.int64).clip(0, E - 1))
            w.pull_sync(ks)
            w.wait(w.push(ks, np.ones((len(ks), vlen), np.float32)))
            if i % 4 == 0:
                sess.lookup((E * rng.random(32) ** skew)
                            .astype(np.int64).clip(0, E - 1))
            if i % 10 == 9:
                w.advance_clock()
                srv.wait_sync()
        srv.quiesce()
        t_capture = time.perf_counter() - t0
        plane.close()
        srv.shutdown()
        tr = load_wtrace(path)
        # join the decision trace against the op stream while both
        # files still exist (the labeled-dataset summary the policy
        # lab consumes; docs/OBSERVABILITY.md "Explain a decision")
        ds = export_dataset(load_dtrace(dpath), tr)
    # per_shard_hot_rows: --sys.tier.hot_rows is PER SHARD, so these
    # whole-table fractions divide by the device count (the helper is
    # shared with scripts/trace_replay_check.py)
    candidates = {
        "hot_25pct": {"tier": True,
                      "tier_hot_rows": per_shard_hot_rows(E, 0.25)},
        "hot_50pct": {"tier": True,
                      "tier_hot_rows": per_shard_hot_rows(E, 0.50)},
        "hot_100pct": {"tier": True,
                       "tier_hot_rows": per_shard_hot_rows(E, 1.0)},
    }
    _progress(f"replay phase: ranking {len(candidates)} candidates "
              f"over {len(tr.events)} events")
    # speed 10, not 100: at full compression the replay leaves the
    # background promotion worker no think-time between ops, so every
    # capacity candidate is promotion-bandwidth-bound and the sweep
    # near-ties — 10x keeps the gap shape while letting capacity be
    # the variable under test (docs/REPLAY.md "Choosing a speed")
    art = rank_candidates(tr, candidates, objective="hot_hit_rate",
                          seed=7, speed=10.0)
    # determinism re-verified on the winner (the full guard is
    # scripts/trace_replay_check.py)
    win = art["winner"]
    redo = ReplayEngine(tr, overrides=candidates[win], seed=7,
                        speed=10.0).run()
    deterministic = redo["reads_digest"] == \
        art["candidates"][win]["reads_digest"]
    _progress(f"replay phase: winner {win} "
              f"(hot_hit_rate "
              f"{art['candidates'][win]['score']['hot_hit_rate']}), "
              f"deterministic={deterministic}")
    return {"capture_s": round(t_capture, 3),
            "trace_events": len(tr.events),
            "trace_kinds": tr.kinds(),
            "decisions": {"planes": ds["planes"],
                          "rows": ds["n_rows"],
                          "unresolved": ds["n_unresolved"],
                          "regretted": ds["n_regretted"],
                          "columns": len(ds["columns"])},
            "replay_deterministic": bool(deterministic),
            "winner": win,
            "ranking": art["ranking"],
            "objective": art["objective"],
            "scores": {n: art["candidates"][n]["score"]
                       for n in candidates},
            "replay_wall_s": {n: art["candidates"][n]["wall_s"]
                              for n in candidates}}


def bench_northstar(E=8192, vlen=16, batch=32, rate=2000.0,
                    segment_s=3.0):
    """North-star phase (ISSUE 20): the train-while-serve streaming
    scenario (adapm_tpu/stream/scenario.py) — continuous event ingest
    + multi-tenant `lookup_bags` serving + periodic incremental
    checkpoints + a mid-stream kill/restore drill + the FreshnessSLO
    closed loop — then the captured `.wtrace` replayed TWICE to pin
    the determinism digest. The artifact carries events/s, served
    P50/P99, trailing-window freshness P50/P99 (the number ISSUE 20's
    acceptance compares against r18's uncontrolled 3.19 s P99),
    recovery_s, and the drill's replay accounting."""
    import tempfile

    from adapm_tpu.replay import ReplayEngine, load_wtrace
    from adapm_tpu.stream.scenario import run_northstar

    with tempfile.TemporaryDirectory(prefix="adapm_northstar_") as tmp:
        _progress(f"northstar phase: running scenario ({E} keys, "
                  f"2 x {segment_s}s segments)")
        out = run_northstar(num_keys=E, vlen=vlen, batch=batch,
                            rate=rate, segment_s=segment_s,
                            workdir=tmp)
        # canonical-wtrace determinism (ISSUE 20 satellite): the
        # captured stream replays to the SAME reads digest twice —
        # the full sweep guard is scripts/trace_replay_check.py; this
        # pins the northstar capture specifically
        tr = load_wtrace(out["wtrace_path"])
        _progress(f"northstar phase: replaying {len(tr.events)} "
                  "captured events twice")
        r1 = ReplayEngine(tr, seed=7, speed=100.0).run()
        r2 = ReplayEngine(tr, seed=7, speed=100.0).run()
        out["wtrace"] = {
            "events": len(tr.events),
            "kinds": tr.kinds(),
            "reads_digest": r1["reads_digest"],
            "replay_deterministic":
                bool(r1["reads_digest"] == r2["reads_digest"])}
        out["wtrace_path"] = None   # tempdir-bound; shape stays stable
    fr = out["freshness"]
    _progress(f"northstar phase: {out['events_per_sec']} events/s, "
              f"served p99 {out['served_p99_ms']} ms, freshness p99 "
              f"{fr['p99_ms']} ms (target {fr['target_ms']} ms), "
              f"recovery {out['drill']['recovery_s']}s, "
              f"{out['drill']['replayed_events']} replayed, "
              f"deterministic={out['wtrace']['replay_deterministic']}")
    return out


def bench_policy(E=1024, vlen=8, steps=80, skew=6.0):
    """Learned-policy phase (ISSUE 18): capture the decision plane
    under a deliberately starved hot pool (promotion under churn
    evicts rows before they are re-touched, so most tier windows
    resolve with regret), train the per-plane regret scorers offline
    (adapm_tpu/policy), then replay the SAME workload A/B — heuristic
    vs learned tier policy — scored by the decision-regret gauges
    (`score_decisions=True`). The artifact carries the per-plane
    training summary, both candidates' regret rates, the deltas, and
    the value-preservation identity (both modes MUST fold the same
    reads digest: a policy changes what/when, never values —
    docs/POLICY.md)."""
    import tempfile

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.policy import train_policy
    from adapm_tpu.replay import (load_wtrace, per_shard_hot_rows,
                                  rank_candidates)

    with tempfile.TemporaryDirectory(prefix="adapm_policy_") as tmp:
        wpath = os.path.join(tmp, "bench.wtrace")
        dpath = os.path.join(tmp, "bench.dtrace")
        ppath = os.path.join(tmp, "bench.policy.json")
        tiny = max(8, per_shard_hot_rows(E, 0.05))
        _progress(f"policy phase: capturing storm ({E} keys, {steps} "
                  f"steps, starved hot pool {tiny} rows/shard)")
        opts = SystemOptions(sync_max_per_sec=0, prefetch=False,
                             tier=True, tier_hot_rows=tiny,
                             trace_workload=wpath,
                             trace_decisions=dpath)
        srv = adapm_tpu.setup(E, vlen, opts=opts, num_workers=2)
        w0, w1 = srv.make_worker(0), srv.make_worker(1)
        w0.wait(w0.set(np.arange(E), np.ones((E, vlen), np.float32)))
        rng = np.random.default_rng(29)
        for i in range(steps):
            w = w0 if i % 2 == 0 else w1
            ks = np.unique((E * rng.random(24) ** skew)
                           .astype(np.int64).clip(0, E - 1))
            w.pull_sync(ks)
            w.wait(w.push(ks, np.ones((len(ks), vlen), np.float32)))
            if i % 4 == 0:
                w.intent(ks, w.current_clock, w.current_clock + 4)
                w.advance_clock()
            srv.wait_sync()
        srv.quiesce()
        srv.shutdown()
        tr = load_wtrace(wpath)
        _progress("policy phase: training per-plane policies")
        bundle = train_policy(dpath, wpath, out_path=ppath)
        # A/B while the policy artifact still exists in the tempdir:
        # the learned candidate flips ONLY the tier plane (holds
        # background promotions — unconditionally value-preserving)
        art = rank_candidates(
            tr,
            {"heuristic": {},
             "learned": {"policy_tier": "learned",
                         "policy_file": ppath}},
            objective="regret_rate_tier", seed=7, speed=10.0,
            score_decisions=True)
    heur = art["candidates"]["heuristic"]
    lrn = art["candidates"]["learned"]
    regret_keys = ("regret_rate_reloc", "regret_rate_tier",
                   "regret_rate_sync", "regret_rate_serve")
    deltas = {k: (round(lrn["score"][k] - heur["score"][k], 4)
                  if lrn["score"].get(k) is not None
                  and heur["score"].get(k) is not None else None)
              for k in regret_keys}
    value_preserving = heur["reads_digest"] == lrn["reads_digest"]
    _progress(f"policy phase: winner {art['winner']} (tier regret "
              f"heuristic {heur['score']['regret_rate_tier']} vs "
              f"learned {lrn['score']['regret_rate_tier']}), "
              f"value_preserving={value_preserving}")
    return {"train": bundle.meta["train"],
            "dataset_rows": bundle.meta["dataset_rows"],
            "truncated_rows": bundle.meta["truncated_rows"],
            "winner": art["winner"],
            "objective": art["objective"],
            "regret": {"heuristic": {k: heur["score"][k]
                                     for k in regret_keys},
                       "learned": {k: lrn["score"][k]
                                   for k in regret_keys}},
            "regret_delta": deltas,
            "value_preserving": bool(value_preserving)}


def bench_tier(E=40_000, d=32, B=1024, steps=60, warmup=20,
               skew=16.0):
    """Tiered-storage phase (ISSUE 5): pull/push throughput of the
    skewed KGE-shaped workload (rows = [emb | adagrad], power-law key
    skew) at device-hot capacity in {100%, 50%, 25%} of the keys vs the
    untiered baseline. One fixed batch schedule is shared by every
    configuration; adaptation (score-driven promotion) runs during
    warmup via tier.maintain() and stays live (the maintenance worker)
    during the timed window. The artifact records per-config hot-hit
    rate and the cold-serve latency histogram P50/P99 alongside the
    throughput ratios — the acceptance floor is hot-50% >= 0.8x
    untiered."""
    import adapm_tpu
    import jax
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.obs.metrics import hist_percentile

    L = 2 * d
    S = len(jax.devices())
    rng = np.random.default_rng(0)
    # zipf-ish schedule: key = E * u^skew -> P(top 25%) = 0.25^(1/skew)
    sched = [(E * rng.random(B) ** skew).astype(np.int64).clip(0, E - 1)
             for _ in range(warmup + steps)]
    init = np.random.default_rng(1).normal(
        size=(E, L)).astype(np.float32)
    upd = (np.random.default_rng(2).normal(
        size=(B, L)).astype(np.float32) * 1e-3)

    def run_config(hot_frac):
        tier = hot_frac is not None
        hot_rows = max(8, -(-int(E * hot_frac) // S)) if tier else 0
        srv = adapm_tpu.setup(E, L, opts=SystemOptions(
            sync_max_per_sec=0, prefetch=False,
            tier=tier, tier_hot_rows=hot_rows))
        w = srv.make_worker(0)
        slab = 50_000
        for lo in range(0, E, slab):
            hi = min(lo + slab, E)
            w.set(np.arange(lo, hi), init[lo:hi])
        for b in sched[:warmup]:
            w.pull_sync(b)
            w.push(b, upd)
            if tier:
                srv.tier.maintain()
        srv.block()
        h0 = c0 = 0
        if tier:
            st = srv.stores[0]
            h0, c0 = st.tier_hot_hits, st.tier_cold_hits
        t0 = time.perf_counter()
        for b in sched[warmup:]:
            w.pull_sync(b)
            w.push(b, upd)
        srv.block()
        dt = time.perf_counter() - t0
        out = {"keys_per_sec": round(2 * steps * B / dt, 1)}
        if tier:
            st = srv.stores[0]
            dh = st.tier_hot_hits - h0
            dc = st.tier_cold_hits - c0
            out["hot_hit_rate"] = round(dh / max(1, dh + dc), 4)
            out["hot_rows_per_shard"] = hot_rows
            cold = srv.obs.find("tier.cold_serve_s")
            snap = cold.snap() if cold is not None else 0
            if snap and snap.get("count"):
                out["cold_serve_p50_ms"] = round(
                    1e3 * hist_percentile(snap, 0.50), 3)
                out["cold_serve_p99_ms"] = round(
                    1e3 * hist_percentile(snap, 0.99), 3)
            # the tier metrics snapshot rides in the artifact
            out["tier_metrics"] = srv.metrics_snapshot()["tier"]
        srv.shutdown()
        return out

    _progress(f"tier phase: untiered baseline ({E} keys, B={B})")
    base = run_config(None)
    res = {"keys_per_lookup": B,
           "untiered_keys_per_sec": base["keys_per_sec"],
           "tier": {}}
    for frac in (1.0, 0.5, 0.25):
        _progress(f"tier phase: hot capacity {int(frac * 100)}%")
        res["tier"][f"hot_{int(frac * 100)}pct"] = run_config(frac)
    r50 = res["tier"]["hot_50pct"]["keys_per_sec"] / \
        max(1e-9, base["keys_per_sec"])
    res["ratio_50pct_vs_untiered"] = round(r50, 3)
    _progress(f"tier phase: hot-50% ratio {r50:.3f} "
              f"(hit rate {res['tier']['hot_50pct'].get('hot_hit_rate')})")
    return res


def bench_exec(E=40_000, d=32, B=1024, steps=60, warmup=20,
               skew=16.0, hot_frac=0.25):
    """Unified-executor phase (ISSUE 6): wall time of a tiered
    KGE-shaped workload WITH PROMOTION CHURN — zipf pull+push over a
    25%-capacity hot pool, the maintenance worker kicked throughout, so
    promotion batch prep genuinely competes with the training thread's
    dispatches — overlapped (the multi-stream executor default) vs
    serialized (--sys.exec.single_stream, one worker — background
    programs strictly one at a time, no double-buffering). One fixed batch schedule is shared by both
    configurations; the drain of the queued maintenance backlog is
    INSIDE the timed window (a serialized executor pays it at the end,
    the overlapped one retires it concurrently — GraphVite's episodic
    transfer/compute overlap). The artifact records both wall times,
    the ratio, the overlap_fraction gauge under churn, and the
    overlapped server's full exec metrics section."""
    import adapm_tpu
    import jax
    from adapm_tpu.config import SystemOptions

    L = 2 * d
    S = len(jax.devices())
    rng = np.random.default_rng(0)
    sched = [(E * rng.random(B) ** skew).astype(np.int64).clip(0, E - 1)
             for _ in range(warmup + steps)]
    init = np.random.default_rng(1).normal(
        size=(E, L)).astype(np.float32)
    upd = (np.random.default_rng(2).normal(
        size=(B, L)).astype(np.float32) * 1e-3)
    hot_rows = max(8, -(-int(E * hot_frac) // S))

    def run_config(single_stream):
        srv = adapm_tpu.setup(E, L, opts=SystemOptions(
            sync_max_per_sec=0, prefetch=False,
            tier=True, tier_hot_rows=hot_rows,
            exec_single_stream=single_stream))
        w = srv.make_worker(0)
        slab = 50_000
        for lo in range(0, E, slab):
            hi = min(lo + slab, E)
            w.set(np.arange(lo, hi), init[lo:hi])
        for b in sched[:warmup]:
            w.pull_sync(b)
            w.push(b, upd)
            srv.tier.maintain()
        srv.block()
        t0 = time.perf_counter()
        for i, b in enumerate(sched[warmup:]):
            w.pull_sync(b)
            w.push(b, upd)
            if i % 4 == 0:
                srv.tier.engine.kick()
        srv.exec.drain("tier", timeout=120)
        srv.exec.drain("tier_commit", timeout=120)
        srv.block()
        dt = time.perf_counter() - t0
        out = {"wall_s": round(dt, 4),
               "keys_per_sec": round(2 * steps * B / dt, 1),
               "overlap_fraction":
                   round(srv.exec.overlap_fraction(), 4),
               "exec_stats": {k: round(v, 4) if isinstance(v, float)
                              else v
                              for k, v in srv.exec.stats().items()}}
        if not single_stream:
            out["metrics"] = srv.metrics_snapshot()
        srv.shutdown()
        return out

    _progress(f"exec phase: serialized single-stream fallback "
              f"({E} keys, B={B}, hot {int(hot_frac * 100)}%)")
    ser = run_config(True)
    _progress("exec phase: overlapped multi-stream default")
    over = run_config(False)
    ratio = over["wall_s"] / max(1e-9, ser["wall_s"])
    _progress(f"exec phase: overlapped/serialized wall ratio "
              f"{ratio:.3f}, overlap_fraction "
              f"{over['overlap_fraction']:.3f}")
    return {"keys_per_lookup": B,
            "hot_rows_per_shard": hot_rows,
            "overlapped": over,
            "serialized": ser,
            "overlapped_vs_serialized_wall_ratio": round(ratio, 3)}


def bench_episodic(E=40_000, d=16, B=512, steps=48, warmup=12,
                   skew=16.0, hot_frac=0.25, episode_batches=8):
    """Episodic-execution phase (ISSUE 14): wall time of a
    BEYOND-HOT-CAPACITY fused-step workload (zipf keys over a
    25%-capacity hot pool, so every batch carries cold rows) run
    EPISODICALLY (device/episode.py: promotion + key staging of window
    N+1 on the `episode` stream overlapping window N's step commits on
    `episode_commit`) vs strictly SEQUENTIALLY (plain runner calls —
    each step pays its forced promotion inline). One fixed batch
    schedule is shared; the drain of the episode streams and the final
    block are INSIDE both timed windows. The artifact records both
    walls, the episodic/sequential ratio (the perf payload: < 1.0 =
    prep genuinely overlapped compute), the episodic server's
    exec.overlap_fraction, and the episode metrics section."""
    import adapm_tpu
    import jax
    import jax.numpy as jnp
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.device import EpisodicRunner
    from adapm_tpu.ops import DeviceRoutedRunner

    L = 2 * d
    S = len(jax.devices())
    rng = np.random.default_rng(0)

    def batch():
        return {
            "a": (E * rng.random(B) ** skew).astype(np.int64)
            .clip(0, E - 1),
            "b": (E * rng.random(B) ** skew).astype(np.int64)
            .clip(0, E - 1)}

    sched = [batch() for _ in range(warmup + steps)]
    init = np.random.default_rng(1).normal(size=(E, L)).astype(np.float32)
    init[:, d:] = np.abs(init[:, d:]) + 1e-3  # AdaGrad acc columns
    hot_rows = max(8, -(-int(E * hot_frac) // S))

    def loss_fn(embs, aux):
        return jnp.mean(jnp.sum(embs["a"] * embs["b"], axis=-1))

    def run_config(episodic: bool):
        srv = adapm_tpu.setup(E, L, opts=SystemOptions(
            sync_max_per_sec=0, prefetch=False,
            tier=True, tier_hot_rows=hot_rows,
            episode_batches=episode_batches))
        w = srv.make_worker(0)
        slab = 50_000
        for lo in range(0, E, slab):
            hi = min(lo + slab, E)
            w.set(np.arange(lo, hi), init[lo:hi])
        runner = DeviceRoutedRunner(srv, loss_fn, {"a": 0, "b": 0},
                                    {"a": d, "b": d}, shard=0, seed=3)
        ep = EpisodicRunner(runner) if episodic else None
        for b in sched[:warmup]:
            runner(b, None, 1e-3)
            srv.tier.maintain()
        srv.block()
        t0 = time.perf_counter()
        if episodic:
            losses = ep.run(sched[warmup:], lr=1e-3)
            float(losses[-1])
        else:
            loss = None
            for b in sched[warmup:]:
                loss = runner(b, None, 1e-3)
            float(loss)
        srv.exec.drain("episode_commit", timeout=120)
        srv.block()
        dt = time.perf_counter() - t0
        out = {"wall_s": round(dt, 4),
               "steps_per_sec": round(steps / dt, 2),
               "overlap_fraction":
                   round(srv.exec.overlap_fraction(), 4)}
        if episodic:
            snap = srv.metrics_snapshot()
            out["episode_metrics"] = snap["episode"]
            out["device_metrics"] = snap["device"]
        srv.shutdown()
        return out

    _progress(f"episodic phase: sequential baseline ({E} keys, B={B}, "
              f"hot {int(hot_frac * 100)}%)")
    seq = run_config(False)
    _progress("episodic phase: double-buffered episodic run")
    epi = run_config(True)
    ratio = epi["wall_s"] / max(1e-9, seq["wall_s"])
    _progress(f"episodic phase: episodic/sequential wall ratio "
              f"{ratio:.3f}, overlap_fraction "
              f"{epi['overlap_fraction']:.3f}")
    return {"batches_per_episode": episode_batches,
            "hot_rows_per_shard": hot_rows,
            "episodic": epi,
            "sequential": seq,
            "overlap_fraction": epi["overlap_fraction"],
            "episodic_vs_sequential_wall_ratio": round(ratio, 3)}


def bench_w2v(V=100_000, d=128, B=8192, N=5, steps=40, warmup=4,
              scan_steps=1) -> float:
    """word2vec SGNS fused-step throughput (pairs/sec) with on-device
    unigram^0.75 alias negatives — the second headline workload.
    scan_steps > 1: K batches per lax.scan dispatch (runner.run_scan),
    the --scan_steps lever of the w2v app (VERDICT r4 item 6)."""
    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.models.sgns import build_alias_table, sgns_loss, \
        syn1_key
    from adapm_tpu.ops import DeviceRoutedRunner

    num_keys = 2 * V
    srv = adapm_tpu.setup(num_keys, 2 * d,
                          opts=SystemOptions(cache_slots_per_shard=1,
                                             sync_max_per_sec=0))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    slab = 100_000
    for lo in range(0, num_keys, slab):
        hi = min(lo + slab, num_keys)
        vals = rng.normal(size=(hi - lo, 2 * d)).astype(np.float32) * 0.05
        vals[:, d:] = 1e-6
        w.set(np.arange(lo, hi), vals)
    srv.block()
    counts = 1.0 / (np.arange(V) + 10.0)  # zipf corpus frequencies
    runner = DeviceRoutedRunner(
        srv, sgns_loss, role_class={"center": 0, "ctx": 0, "neg": 0},
        role_dim={k: d for k in ("center", "ctx", "neg")},
        neg_role="neg", neg_shape=(B, N),
        neg_population=syn1_key(np.arange(V)),
        neg_alias=build_alias_table(counts))

    batches = [{"center": 2 * _skewed_keys(rng, V, B),
                "ctx": 2 * _skewed_keys(rng, V, B) + 1}
               for _ in range(4)]

    if scan_steps > 1:
        windows = [[batches[(i + j) % 4] for j in range(scan_steps)]
                   for i in range(2)]

        def dispatch(i):
            return runner.run_scan(windows[i % 2], None, 0.05)
    else:
        def dispatch(i):
            return runner(batches[i % 4], None, 0.05)

    def timed(n):
        t0 = time.perf_counter()
        loss = None
        for i in range(n):
            loss = dispatch(i)
        float(np.asarray(loss).ravel()[-1])
        return time.perf_counter() - t0

    for _ in range(warmup):
        dispatch(0)
    timed(1)
    t_short = timed(steps // 4)
    t_long = timed(steps)
    dt = (t_long - t_short) / (steps - steps // 4)
    srv.shutdown()
    return B * scan_steps / dt


def bench_fault(E=40_000, vlen=32, dirty_frac=0.01):
    """Robustness phase (ISSUE 10): incremental-vs-full checkpoint
    bytes and crash-recovery wall time. Host-CPU by design — the
    numbers are file bytes and a restore wall time dominated by host
    serialization, not device compute.

    Shape: full base checkpoint of an E x vlen model, a
    `dirty_frac` trickle, then a dirty-slot delta; the server is shut
    down (the crash) and a fresh one restores the chain. The artifact
    carries the bytes ratio (the incremental lever) and recovery_s
    (ROADMAP item 5's recovery-time metric)."""
    import tempfile

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.fault import IncrementalCheckpointer, restore_chain
    rng = np.random.default_rng(0)
    opts = SystemOptions(sync_max_per_sec=0, prefetch=False)
    _progress(f"fault phase: building server ({E} keys x {vlen})")
    srv = adapm_tpu.setup(E, vlen, opts=opts, num_workers=2)
    w = srv.make_worker(0)
    w.set(np.arange(E), rng.normal(size=(E, vlen)).astype(np.float32))
    chain = tempfile.mkdtemp(prefix="adapm_bench_fault_")
    ck = IncrementalCheckpointer(srv, chain)
    t0 = time.perf_counter()
    base = ck.save()
    base_save_s = time.perf_counter() - t0
    n_dirty = max(1, int(E * dirty_frac))
    dirty = rng.choice(E, size=n_dirty, replace=False)
    w.push(dirty, np.ones((n_dirty, vlen), np.float32))
    t0 = time.perf_counter()
    delta = ck.save()
    delta_save_s = time.perf_counter() - t0
    expected = np.asarray(srv.read_main(np.arange(256)))
    _progress(f"fault phase: base {base['bytes']}B, "
              f"{dirty_frac:.0%}-dirty delta {delta['bytes']}B; "
              f"killing + restoring")
    srv.shutdown()
    srv2 = adapm_tpu.setup(E, vlen, opts=SystemOptions(
        sync_max_per_sec=0, prefetch=False), num_workers=2)
    recovery_s = restore_chain(srv2, chain)
    assert np.array_equal(
        np.asarray(srv2.read_main(np.arange(256))), expected), \
        "post-restore sample not bit-exact"
    out = {"keys": E, "vlen": vlen,
           "full_bytes": base["bytes"],
           "delta_bytes": delta["bytes"],
           "dirty_slots": delta["slots"],
           "incremental_ratio": round(
               delta["bytes"] / base["bytes"], 5),
           "base_save_s": round(base_save_s, 4),
           "delta_save_s": round(delta_save_s, 4),
           "recovery_s": round(recovery_s, 4),
           "metrics": srv2.metrics_snapshot()}
    _progress(f"fault phase: ratio {out['incremental_ratio']} "
              f"recovery_s {out['recovery_s']}")
    srv2.shutdown()
    return out


def bench_cpu_torch(E=200_000, R=1_000, d=128, B=4096, N=32,
                    steps=3) -> float:
    """Measured CPU baseline: the same ComplEx+AdaGrad batch step written
    the way a competent torch user would (batched gathers, autograd on the
    gathered rows, index_add scatter) on this host's CPU. Stronger per core
    than the reference's per-triple C++ loop (kge.cc:437-531), so scaling
    it to the paper's cluster size gives a *conservative* baseline."""
    import torch

    # measure true single-core throughput (dividing an all-thread time by
    # the thread count would assume perfect intra-op scaling and inflate
    # vs_baseline on many-core hosts)
    torch.set_num_threads(1)
    torch.manual_seed(0)
    ent = torch.randn(E, 2 * d) * 0.1
    rel = torch.randn(R, 2 * d) * 0.1
    ent_a = torch.full((E, 2 * d), 1e-6)
    rel_a = torch.full((R, 2 * d), 1e-6)
    lr, eps = 0.1, 1e-10

    def cscore(s, r, o):
        sr, si = s[..., :d], s[..., d:]
        rr, ri = r[..., :d], r[..., d:]
        orr, oi = o[..., :d], o[..., d:]
        return (sr * rr * orr + si * rr * oi
                + sr * ri * oi - si * ri * orr).sum(-1)

    def step():
        s = torch.randint(0, E, (B,))
        r = torch.randint(0, R, (B,))
        o = torch.randint(0, E, (B,))
        n = torch.randint(0, E, (B, N))
        se = ent[s].requires_grad_(True)
        re_ = rel[r].requires_grad_(True)
        oe = ent[o].requires_grad_(True)
        ne = ent[n].requires_grad_(True)
        pos = cscore(se, re_, oe)
        neg = cscore(ne, re_.unsqueeze(1), oe.unsqueeze(1))
        loss = torch.nn.functional.softplus(-pos).sum() + \
            torch.nn.functional.softplus(neg).sum()
        loss.backward()

        def adagrad(table, acc, idx, g):
            acc.index_add_(0, idx, g * g)
            table.index_add_(0, idx, -lr * g / torch.sqrt(acc[idx] + eps))

        adagrad(ent, ent_a, s, se.grad)
        adagrad(rel, rel_a, r, re_.grad)
        adagrad(ent, ent_a, o, oe.grad)
        adagrad(ent, ent_a, n.reshape(-1), ne.grad.reshape(-1, 2 * d))

    step()  # warmup
    # per-step MIN: a loaded host would otherwise deflate the baseline
    # and flatter vs_baseline (observed 1.7x swing while a test suite
    # ran concurrently); the fastest step is the fairest estimate of the
    # hardware's single-core capability
    best = float("inf")
    for _ in range(steps):
        t0 = time.perf_counter()
        step()
        best = min(best, time.perf_counter() - t0)
    return B / best


# ---------------------------------------------------------------- phases
# Re-entry points: `python bench.py --phase NAME` runs one phase and prints
# one JSON line on stdout. The driver (main) runs each in a subprocess with
# a hard timeout so a wedged backend cannot take down the whole artifact.

def _phase_probe():
    import jax
    devs = jax.devices()
    return {"platform": devs[0].platform, "n_devices": len(devs)}


# Degraded (CPU-fallback) sizes: the full-size kge phase needs ~10 min
# just to compile+warm on the 8-virtual-shard host mesh, so when the TPU
# is unavailable the driver sets ADAPM_BENCH_SMALL=1 and the phases run a
# small (honestly-labeled) configuration that keeps the artifact alive.
_SMALL = {"E": 50_000, "d": 32, "B": 1024, "N": 8}


def _kge_sizes() -> dict:
    if os.environ.get("ADAPM_BENCH_SMALL"):
        return dict(_SMALL)
    return {}


def _phase_kge():
    sz = _kge_sizes()
    tput, srv = bench_tpu(steps=16 if sz else 50, warmup=2 if sz else 5,
                          **sz)
    out = {"tput": tput,
           "rounds": srv.sync.stats.rounds,
           "intents_processed": srv.sync.stats.intents_processed,
           # end-of-run telemetry snapshot (docs/OBSERVABILITY.md): the
           # BENCH artifact carries hit rates / latency / staleness
           # alongside throughput
           "metrics": srv.metrics_snapshot()}
    if sz:
        out["small_sizes"] = sz
    srv.shutdown()
    return out


def _phase_prefetch():
    # intent-driven prefetch pipeline (r6 tentpole): the per-step loop
    # with staged key uploads + the planner round on the pipeline's
    # background executor. Runs under ADAPM_BENCH_SMALL=1 too, so every
    # degraded/CI bench exercises the pipeline (smoke coverage).
    sz = _kge_sizes()
    tput, srv = bench_tpu(steps=16 if sz else 50, warmup=2 if sz else 5,
                          prefetch=True, **sz)
    srv.prefetch.flush()
    out = {"tput": tput,
           "rounds": srv.sync.stats.rounds,
           "pipeline": srv.prefetch.report(),
           "plan_cache": srv._plan_cache.stats()
           if srv._plan_cache is not None else None,
           "metrics": srv.metrics_snapshot()}
    if sz:
        out["small_sizes"] = sz
    srv.shutdown()
    return out


def _phase_scan():
    # K-step scan window (VERDICT r3 item 2): one dispatch trains 8 steps
    sz = _kge_sizes()
    tput, srv = bench_tpu(steps=8 if sz else 12, scan_steps=8, **sz)
    srv.shutdown()
    return {"tput": tput}


def _phase_dedup():
    # dedup lever (docs/PERF.md): all-unique batches bound what a perfect
    # in-step dedup could gain over the skewed batches
    sz = _kge_sizes()
    tput, srv = bench_tpu(steps=8 if sz else 24, dedup_batches=True, **sz)
    srv.shutdown()
    return {"tput": tput}


def _phase_pm():
    import jax
    out = bench_adaptive_pm()
    out["virtual_shards"] = len(jax.devices("cpu"))
    return out


def _phase_mgmt():
    import jax
    sz = {"replicas": 20_000, "rounds": 24, "trickle": 256} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_mgmt(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_compress():
    import jax
    sz = {"replicas": 8_000, "rounds": 10, "cold_E": 8_000,
          "drift_steps": 8} if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_compress(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_serve():
    import jax
    sz = {"E": 8_000, "lookups_per_client": 20} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_serve(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_bag():
    import jax
    sz = {"E": 6_000, "L": 64, "nbags": 64, "rounds": 10} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_bag(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_tier():
    import jax
    sz = {"E": 10_000, "B": 512, "steps": 30, "warmup": 12} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_tier(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_exec():
    import jax
    sz = {"E": 10_000, "B": 512, "steps": 30, "warmup": 12} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_exec(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_episodic():
    import jax
    sz = {"E": 10_000, "B": 256, "steps": 32, "warmup": 8,
          "episode_batches": 4} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_episodic(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_fault():
    import jax
    sz = {"E": 8_000} if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_fault(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_replay():
    import jax
    sz = {"E": 2_048, "steps": 100} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_replay(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_northstar():
    import jax
    sz = {"E": 2_048, "vlen": 8, "batch": 16, "rate": 1000.0,
          "segment_s": 2.0} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_northstar(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_policy():
    import jax
    sz = {"steps": 60} if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_policy(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_w2v():
    if os.environ.get("ADAPM_BENCH_SMALL"):
        small = dict(V=20_000, d=64, B=2048, warmup=2)
        per_step = bench_w2v(steps=16, **small)
        scan8 = bench_w2v(steps=8, scan_steps=8, **small)
    else:
        per_step = bench_w2v()
        scan8 = bench_w2v(steps=12, scan_steps=8)
    # "pairs_per_sec" stays the PER-STEP number: earlier rounds recorded
    # it that way, and a best-of here would mask per-step regressions
    return {"pairs_per_sec": per_step,
            "scan8_pairs_per_sec": scan8,
            "scan_gain": round(scan8 / per_step - 1.0, 3)}


def bench_net(E=2_048, L=16, rounds=4, batch=256):
    """NetPort loopback transport (ISSUE 19; docs/NETWORK.md): two full
    Servers in one process wired through the loopback fabric. Measures
    cross-node push/sync wire throughput under injected wire faults
    (drop/dup/delay — the retransmit + dedup machinery pays its way or
    shows up here), then kills one node and records the dead-peer
    failover wall (detection -> replicas promoted = net.failover_s)."""
    import numpy as np

    from adapm_tpu.base import CLOCK_MAX
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.net import LoopbackCluster

    cl = LoopbackCluster(
        2, num_keys=E, value_lengths=L,
        opts_factory=lambda r: SystemOptions(
            sync_max_per_sec=0, prefetch=False,
            fault_spec="net.send=0.02,net.recv=0.02,net.dup=0.05"),
        heartbeat_ms=40.0)
    allk = np.arange(E, dtype=np.int64)

    def prep(rank, srv):
        w = srv.make_worker(0)
        if rank == 0:
            w.wait(w.set(allk, np.zeros((E, L), np.float32)))
        srv.barrier()
        theirs = allk[srv.glob.home_proc(allk) == 1]
        if rank == 1:
            w.intent(theirs, 0, CLOCK_MAX)
            srv.wait_sync()
        srv.barrier()
        if rank == 0:
            w.intent(theirs, 0, CLOCK_MAX)
            srv.wait_sync()
        srv.barrier()

    cl.run(prep)

    def storm(rank, srv):
        w = srv.make_worker(0)
        rng = np.random.default_rng(100 + rank)
        for _ in range(rounds):
            keys = np.sort(rng.choice(E, size=batch,
                                      replace=False)).astype(
                np.int64)
            vals = rng.integers(-4, 5, size=(batch, L)).astype(
                np.float32)
            w.wait(w.push(keys, vals))
            srv.wait_sync()
            srv.barrier()
        return None

    t0 = time.perf_counter()
    cl.run(storm)
    storm_s = time.perf_counter() - t0
    s = cl.servers[0].net.stats()
    wire_msgs = s["msgs_out"] + s["msgs_in"]
    wire_bytes = s["bytes_out"] + s["bytes_in"]

    srv0 = cl.servers[0]
    cl.kill(1)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and \
            srv0.net.stats()["failovers"] == 0:
        time.sleep(0.02)
    f = srv0.net.stats()
    out = {
        "storm_s": round(storm_s, 3),
        "push_keys_per_s": round(2 * rounds * batch / storm_s),
        "wire_msgs_per_s": round(wire_msgs / storm_s),
        "wire_mb_per_s": round(wire_bytes / storm_s / 1e6, 2),
        "retransmits": s["retransmits"],
        "dup_suppressed": s["dup_suppressed"],
        "failover_s": round(f["failover_s"], 4),
        "promoted_keys": f["promoted_keys"],
        "lost_keys": f["lost_keys"],
    }
    cl.shutdown(ranks=[0])
    return out


def _phase_net():
    import jax
    sz = {"E": 512, "rounds": 2, "batch": 64} \
        if os.environ.get("ADAPM_BENCH_SMALL") else {}
    out = bench_net(**sz)
    out["virtual_shards"] = len(jax.devices("cpu"))
    if sz:
        out["small_sizes"] = sz
    return out


def _phase_cpu():
    # measured per-core CPU throughput of a strong batched torch
    # implementation of the same step; the paper's 8-node x 8-thread
    # cluster is modeled as 64 such cores (conservative: AdaPM's
    # per-triple C++ loop and network overhead are both slower per core).
    # The reference binary itself cannot be built in this image — its
    # ZMQ/Boost/Eigen dependencies are absent and installs are forbidden
    # (BASELINE.md "Measured baselines").
    return {"per_core_triples_per_sec": bench_cpu_torch()}


_PHASES = {"probe": _phase_probe, "kge": _phase_kge,
           "prefetch": _phase_prefetch, "scan": _phase_scan,
           "dedup": _phase_dedup, "pm": _phase_pm, "mgmt": _phase_mgmt,
           "compress": _phase_compress, "serve": _phase_serve,
           "bag": _phase_bag,
           "tier": _phase_tier, "exec": _phase_exec,
           "episodic": _phase_episodic,
           "fault": _phase_fault, "net": _phase_net,
           "replay": _phase_replay,
           "policy": _phase_policy,
           "northstar": _phase_northstar,
           "w2v": _phase_w2v, "cpu": _phase_cpu}

# generous per-phase walls: a healthy phase finishes in a fraction of
# these; a wedged relay burns one wall once, then the driver degrades
_TIMEOUTS = {"probe": 120, "kge": 1200, "prefetch": 1200, "scan": 900,
             "dedup": 900, "pm": 900, "mgmt": 900, "compress": 900,
             "serve": 900, "bag": 900, "tier": 900, "exec": 900,
             "episodic": 900,
             "fault": 900, "net": 900, "replay": 900, "policy": 900,
             "northstar": 900,
             "w2v": 900, "cpu": 600}

_CPU_ENV = {"JAX_PLATFORMS": "cpu", "ADAPM_PLATFORM": "cpu",
            "ADAPM_BENCH_SMALL": "1"}


def _run_phase(name: str, env_extra: dict | None = None) -> dict:
    """Run one phase in a subprocess; never raises. Returns the phase's
    JSON dict, or {"error": ...} on timeout / crash / unparseable output."""
    _progress(f"phase {name}: starting "
              f"(timeout {_TIMEOUTS[name]}s, env {env_extra or {}})")
    env = dict(os.environ)
    env.update(env_extra or {})
    cmd = [sys.executable, os.path.abspath(__file__), "--phase", name]
    try:
        p = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=_TIMEOUTS[name])
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"")[-800:] if isinstance(e.stderr, bytes)
                else (e.stderr or "")[-800:])
        _progress(f"phase {name}: TIMEOUT after {_TIMEOUTS[name]}s")
        return {"error": "timeout", "timeout_s": _TIMEOUTS[name],
                "stderr_tail": str(tail)}
    except Exception as e:  # spawn failure — keep the artifact alive
        return {"error": f"spawn: {e!r}"}
    if p.stderr:
        sys.stderr.write(p.stderr[-4000:])
        sys.stderr.flush()
    if p.returncode != 0:
        _progress(f"phase {name}: rc={p.returncode}")
        return {"error": f"rc={p.returncode}",
                "stderr_tail": p.stderr[-800:]}
    try:
        out = json.loads(p.stdout.strip().splitlines()[-1])
    except Exception:
        return {"error": "unparseable", "stdout_tail": p.stdout[-800:]}
    _progress(f"phase {name}: done {out}")
    return out


def _ok(r: dict) -> bool:
    return "error" not in r


def main():
    results: dict = {}
    transients: dict = {}
    # 0) Setup-death probe (ISSUE 14 satellite; the bench r04 mode: the
    # TPU path ABORTING at client construction, before any phase runs).
    # xla_compat.probe_device_backend checks the default backend in a
    # throwaway subprocess; a definitive setup death records the NAMED
    # error and `backend: skipped` in the artifact instead of dying —
    # the device phases then run honestly on the host CPU.
    from xla_compat import probe_device_backend
    verdict, detail = probe_device_backend()
    if verdict is not True:
        results["backend"] = "skipped"
        results["backend_error"] = \
            f"AcceleratorUnavailableError: {detail}"
        _progress(f"backend skipped ({detail}); device phases degrade "
                  f"to JAX_PLATFORMS=cpu")
        probe = {"error": results["backend_error"]}
        tpu_ok = False
    else:
        # 1) Probe the default backend IN-PHASE with a hard timeout. A
        # wedged TPU relay hangs jax.devices() forever (observed
        # r4/r5); in that case every device phase reruns on the host
        # CPU so the round still produces a parseable, honestly-labeled
        # artifact.
        probe = _run_phase("probe")
        tpu_ok = _ok(probe) and probe.get("platform") not in ("cpu", None)
        results["backend"] = probe.get("platform", "cpu") if _ok(probe) \
            else "skipped"
    dev_env: dict | None = None if tpu_ok else dict(_CPU_ENV)
    platform = probe.get("platform") if _ok(probe) else "cpu"
    if not tpu_ok and "backend_error" not in results:
        _progress("backend unavailable or cpu-only: device phases degrade "
                  "to JAX_PLATFORMS=cpu")
    for name in ("kge", "prefetch", "scan", "dedup", "w2v"):
        r = _run_phase(name, dev_env)
        if not _ok(r) and dev_env is None:
            # one retry on the chip first: the relay also fails
            # TRANSIENTLY ("response body closed" mid-compile, observed
            # r5) with the chip healthy — a single retry saves the real
            # TPU number; a true wedge fails it again within the timeout
            _progress(f"phase {name} failed on {platform}; retrying once")
            first_err = r
            r = _run_phase(name, dev_env)
            if _ok(r):
                # recovered: record the transient OUTSIDE the phase_errors
                # sweep so a healthy run isn't misread as a failed one
                transients[name] = first_err
            else:
                results[name + "_tpu_error"] = first_err
        if not _ok(r) and dev_env is None:
            # relay wedged mid-run: degrade the remaining device phases
            # (and retry this one) on CPU rather than burning every wall
            _progress(f"phase {name} failed twice on {platform}; "
                      "degrading remaining device phases to cpu")
            tpu_ok = False
            dev_env = dict(_CPU_ENV)
            results[name + "_tpu_error_retry"] = r
            r = _run_phase(name, dev_env)
        if _ok(r):
            # per-phase provenance: a mid-run degrade must not let small
            # CPU numbers masquerade as (or mix with) full-size chip ones
            r["platform_used"] = platform if dev_env is None else "cpu"
            r["small_sizes_used"] = dev_env is not None
        results[name] = r
    # host-only phases (always CPU by design). The adaptive-pm phase's
    # virtual shard count follows the host's cores: XLA's in-process
    # collective rendezvous has a hard ~40 s watchdog, and 8 concurrent
    # participants on a 1-2 core host stall past it (observed SIGABRT in
    # AllReduceThunk on a 1-core runner); fewer shards still exercise
    # replication/relocation/sync.
    cores = os.cpu_count() or 1
    pm_env = dict(_CPU_ENV)
    pm_shards = 8 if cores >= 4 else 2
    pm_env["XLA_FLAGS"] = mesh_flags(pm_shards)
    results["pm"] = _run_phase("pm", pm_env)
    # management-plane microbench (ISSUE 3): same host-CPU mesh sizing
    # as pm, full-size replica population even on small hosts (the
    # phase measures the host-side planner, not device compute)
    mgmt_env = dict(pm_env)
    mgmt_env.pop("ADAPM_BENCH_SMALL", None)
    results["mgmt"] = _run_phase("mgmt", mgmt_env)
    # compression-plane phase (ISSUE 8): host-CPU by design — the
    # numbers are wire-byte ratios and host bytes/row (size-independent)
    # plus a drift curve; the mode-vs-mode comparison needs one backend
    results["compress"] = _run_phase("compress", pm_env)
    # online-serving phase (ISSUE 4): host-CPU by design — the coalescer
    # and admission queue are host-side, and the comparison against
    # sequential per-request pulls needs both paths on the same backend
    results["serve"] = _run_phase("serve", pm_env)
    # fused bag-read phase (ISSUE 16): host-CPU by design — the
    # fused-vs-hostpool-vs-sequential comparison needs all three read
    # paths on the same backend, and the cost table it calibrates is
    # only meaningful for the backend that measured it
    results["bag"] = _run_phase("bag", pm_env)
    # tiered-storage phase (ISSUE 5): host-CPU by design — the
    # untiered-vs-tiered comparison needs both configurations on the
    # same backend, and the cold path's cost is host<->device traffic
    results["tier"] = _run_phase("tier", pm_env)
    # unified-executor phase (ISSUE 6): host-CPU by design — the
    # overlapped-vs-serialized comparison needs both executor
    # configurations on the same backend, and the overlap being
    # measured is host prep vs device dispatch on this host
    results["exec"] = _run_phase("exec", pm_env)
    # episodic-execution phase (ISSUE 14): host-CPU by design — the
    # episodic-vs-sequential comparison needs both drivers on the same
    # backend, and the overlap measured is host episode prep vs the
    # previous window's device compute on this host
    results["episodic"] = _run_phase("episodic", pm_env)
    # robustness phase (ISSUE 10): host-CPU by design — incremental
    # checkpoint bytes and recovery wall time are host serialization
    results["fault"] = _run_phase("fault", pm_env)
    # transport phase (ISSUE 19): host-CPU by design — two loopback
    # nodes in one process; records storm wire throughput under
    # injected faults and the dead-peer failover wall (net.failover_s)
    results["net"] = _run_phase("net", pm_env)
    # trace-replay phase (ISSUE 15): host-CPU by design — capture +
    # deterministic offline knob sweep are host-driven, and the
    # determinism digest must not depend on which backend ran it
    results["replay"] = _run_phase("replay", pm_env)
    # learned-policy phase (ISSUE 18): host-CPU by design — the A/B is
    # decided by deterministic replay, and the value-preservation
    # digest identity must not depend on which backend ran it
    results["policy"] = _run_phase("policy", pm_env)
    results["cpu"] = _run_phase("cpu")

    def phase_val(name, field):
        return results[name].get(field, 0.0) if _ok(results[name]) else 0.0

    def phase_ctx(name):
        """(platform_used, small) — or (None, None) for a failed phase."""
        r = results[name]
        if not _ok(r):
            return None, None
        return r.get("platform_used"), r.get("small_sizes_used")

    tput = phase_val("kge", "tput")
    tput_pref = phase_val("prefetch", "tput")
    tput_scan = phase_val("scan", "tput")
    tput_unique = phase_val("dedup", "tput")
    w2v = phase_val("w2v", "pairs_per_sec")
    kge_ctx = phase_ctx("kge")
    # ratios are only meaningful between phases run on the SAME platform
    # at the SAME sizes (a mid-run degrade mixes full-size chip numbers
    # with small CPU ones — comparing those is noise, not a gain)
    pref_comparable = tput > 0 and phase_ctx("prefetch") == kge_ctx
    scan_comparable = tput > 0 and phase_ctx("scan") == kge_ctx
    dedup_comparable = tput > 0 and phase_ctx("dedup") == kge_ctx
    pm = results["pm"] if _ok(results["pm"]) else {"error": "pm failed"}
    if _ok(results["kge"]):
        pm = dict(pm)
        pm["rounds"] = results["kge"].get("rounds")
        pm["intents_processed"] = results["kge"].get("intents_processed")
    cpu = (results["cpu"].get("per_core_triples_per_sec", 0.0)
           if _ok(results["cpu"]) else 0.0)
    baseline = 64.0 * cpu
    best = max(tput, tput_scan) if scan_comparable else tput
    if pref_comparable:
        best = max(best, tput_pref)
    kge_on_tpu = _ok(results["kge"]) and \
        results["kge"].get("platform_used") not in ("cpu", None)
    out = {
        "metric": "kge_complex_train_throughput_pm",
        "value": round(best, 1),
        "unit": "triples/sec through the PM (intent+sync in loop; "
                "d=128, B=4096, N=32 negs, E=200k, power-law skew; "
                "best of per-step dispatch, intent-driven prefetch "
                "pipeline, and K=8 scan window)",
        "vs_baseline": (round(best / baseline, 3)
                        if baseline and kge_on_tpu else None),
        "platform": kge_ctx[0] or "none",
        "phase_platforms": {n: phase_ctx(n)[0]
                            for n in ("kge", "prefetch", "scan", "dedup",
                                      "w2v")},
        "per_step_triples_per_sec": round(tput, 1),
        "prefetch_triples_per_sec": round(tput_pref, 1),
        "prefetch_gain": (round(tput_pref / tput - 1.0, 3)
                          if pref_comparable else None),
        # PERF.md "Dispatch overhead": the K=8 scan window is the
        # proven upper bound for hiding dispatch overhead — when even
        # scan gains nothing, the settled per-step loop is already at
        # the compute roofline and NO overlap scheme (prefetch
        # included) has anything to hide. A negative prefetch_gain in
        # that regime is measurement noise, not a regression; the r6
        # >=1.25x acceptance ratio only binds in the gap-exists regime
        # (loaded hosts / relay-attached TPU).
        "prefetch_gain_regime": (
            None if not scan_comparable else
            "dispatch-overhead-gap" if tput_scan / tput - 1.0 > 0.10
            else "compute-roofline (no dispatch gap on this run: scan "
                 "gain within noise, so prefetch_gain is noise too — "
                 "negative values are NOT regressions; see docs/PERF.md"
                 " 'Dispatch overhead')"),
        "prefetch_pipeline": (results["prefetch"].get("pipeline")
                              if _ok(results["prefetch"]) else None),
        "scan8_triples_per_sec": round(tput_scan, 1),
        "scan_gain": (round(tput_scan / tput - 1.0, 3)
                      if scan_comparable else None),
        "pm": pm,
        "mgmt": (results["mgmt"] if _ok(results["mgmt"])
                 else {"error": "mgmt failed"}),
        "compress": (results["compress"] if _ok(results["compress"])
                     else {"error": "compress failed"}),
        "serve": (results["serve"] if _ok(results["serve"])
                  else {"error": "serve failed"}),
        "tier": (results["tier"] if _ok(results["tier"])
                 else {"error": "tier failed"}),
        "exec": (results["exec"] if _ok(results["exec"])
                 else {"error": "exec failed"}),
        "fault": (results["fault"] if _ok(results["fault"])
                  else {"error": "fault failed"}),
        "replay": (results["replay"] if _ok(results["replay"])
                   else {"error": "replay failed"}),
        "policy": (results["policy"] if _ok(results["policy"])
                   else {"error": "policy failed"}),
        "w2v_pairs_per_sec": round(w2v, 1),
        "dedup": {"unique_batch_triples_per_sec": round(tput_unique, 1),
                  "gain_vs_skewed":
                      (round(tput_unique / tput - 1.0, 3)
                       if dedup_comparable else None)},
    }
    if not kge_on_tpu:
        # honest degraded record: the headline number is host-CPU at
        # reduced sizes (ADAPM_BENCH_SMALL), NOT the chip; vs_baseline
        # would compare different platforms/sizes and is voided above
        out["tpu_unavailable"] = True
        out["degraded_sizes"] = _SMALL
        out["probe"] = probe
    elif not tpu_ok:
        # TPU died mid-run: the kge headline IS a chip number, but later
        # phases degraded to CPU (see phase_platforms)
        out["tpu_degraded_midrun"] = True
    if transients:
        # retried-and-recovered relay hiccups: informational, NOT failures
        out["transient_errors"] = transients
    errs = {k: v for k, v in results.items() if not _ok(v)}
    if errs:
        out["phase_errors"] = errs
    print(json.dumps(out))
    if errs:
        # loud failure (ISSUE 18 satellite): the artifact above is
        # still complete evidence, but a run with dead phases must not
        # exit 0 — an outer harness once recorded `"parsed": null`
        # artifacts from benches whose failures only lived in a nested
        # phase_errors dict nothing looked at
        _progress("FAILED phases: " + ", ".join(sorted(errs)))
        return 1
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--phase":
        # The TPU tunnel's sitecustomize bakes jax_platforms into the live
        # config at interpreter start, so the env var alone cannot force
        # CPU (tests/conftest.py documents the same); update the config
        # before any backend is touched.
        _plat = os.environ.get("ADAPM_PLATFORM")
        if _plat:
            import jax
            jax.config.update("jax_platforms", _plat)
        print(json.dumps(_PHASES[sys.argv[2]]()))
    else:
        try:
            rc = main()
        except BaseException as e:
            # the caller must ALWAYS get one parseable JSON line plus a
            # nonzero rc — never a bare traceback it records as
            # `"parsed": null` (ISSUE 18 satellite)
            print(json.dumps({"metric": "kge_complex_train_throughput_pm",
                              "value": 0.0,
                              "error": f"driver crashed: {e!r}"}))
            raise
        sys.exit(rc)
