"""Headline benchmark: fused KGE ComplEx training throughput (triples/sec).

The reference's headline workload is ComplEx KGE training (README.md:140-159;
BASELINE.json north star: beat AdaPM-CPU 8-node wall-clock). This bench runs
the framework's fused train step (gather -> ComplEx score/grad -> AdaGrad ->
scatter-add on the sharded HBM pools, ops/fused.py) on the available device
and reports triples/sec.

vs_baseline: the reference publishes no in-tree numbers (BASELINE.md), so the
baseline is measured here as a proxy: the same per-triple ComplEx+AdaGrad
update in numpy (the reference's CPU compute pattern, kge.cc:415-530, one
triple at a time), scaled x64 for the paper's 8 nodes x 8 worker threads.
vs_baseline = tpu_triples_per_sec / (64 * cpu_single_thread_triples_per_sec).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

import numpy as np


def bench_tpu(E=200_000, R=1_000, d=128, B=4096, N=32, steps=50,
              warmup=5) -> float:
    import jax

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.models import make_kge_loss
    from adapm_tpu.ops import DeviceRoutedRunner

    num_keys = E + R
    srv = adapm_tpu.setup(num_keys, 4 * d,
                          opts=SystemOptions(cache_slots_per_shard=1))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    # initialize in slabs to bound host memory
    slab = 50_000
    for lo in range(0, num_keys, slab):
        hi = min(lo + slab, num_keys)
        vals = rng.normal(size=(hi - lo, 4 * d)).astype(np.float32) * 0.1
        vals[:, 2 * d:] = 1e-6
        w.set(np.arange(lo, hi), vals)
    srv.block()

    # device-routed runner: routing tables mirrored in HBM, negatives drawn
    # in-program (Local sampling scheme on device) — the host ships only the
    # positive triple keys per step
    runner = DeviceRoutedRunner(
        srv, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={k: 2 * d for k in ("s", "r", "o", "neg")},
        neg_role="neg", neg_shape=(B, N),
        neg_population=np.arange(E))

    def batch():
        return {
            "s": rng.integers(0, E, B).astype(np.int64),
            "r": rng.integers(E, E + R, B).astype(np.int64),
            "o": rng.integers(0, E, B).astype(np.int64),
        }

    # Slope timing: some remote-attached TPU runtimes acknowledge
    # block_until_ready before work completes; only a value fetch truly
    # syncs, at a large fixed RTT. Timing two loop lengths and taking the
    # slope removes both the RTT and any warmup from the estimate.
    assert steps >= 4, "slope timing needs steps >= 4 (two loop lengths)"
    batches = [batch() for _ in range(4)]

    def timed(n: int) -> float:
        t0 = time.perf_counter()
        loss = None
        for i in range(n):
            loss = runner(batches[i % len(batches)], None, 0.1)
        float(loss)  # force completion of the whole donated chain
        return time.perf_counter() - t0

    for _ in range(warmup):
        runner(batches[0], None, 0.1)
    timed(1)
    t_short = timed(steps // 4)
    t_long = timed(steps)
    dt = (t_long - t_short) / (steps - steps // 4)
    srv.shutdown()
    return B / dt


def bench_cpu_reference_proxy(E=20_000, R=100, d=128, N=32,
                              triples=300) -> float:
    """Single-thread numpy per-triple ComplEx + AdaGrad (the reference's
    per-data-point CPU hot loop shape, kge.cc train :437-531)."""
    rng = np.random.default_rng(0)
    ent = rng.normal(size=(E, 2 * d)).astype(np.float32) * 0.1
    rel = rng.normal(size=(R, 2 * d)).astype(np.float32) * 0.1
    ent_a = np.full((E, 2 * d), 1e-6, dtype=np.float32)
    rel_a = np.full((R, 2 * d), 1e-6, dtype=np.float32)
    lr, eps = 0.1, 1e-10

    def score_grad(s, r, o):
        sr, si = s[:d], s[d:]
        rr, ri = r[:d], r[d:]
        orr, oi = o[:d], o[d:]
        sc = float((sr * rr * orr + si * rr * oi
                    + sr * ri * oi - si * ri * orr).sum())
        gs = np.concatenate([rr * orr + ri * oi, rr * oi - ri * orr])
        gr = np.concatenate([sr * orr + si * oi, sr * oi - si * orr])
        go = np.concatenate([sr * rr + si * ri, si * rr - sr * ri])
        return sc, gs, gr, go

    def adagrad(table, acc, idx, g):
        acc[idx] += g * g
        table[idx] -= lr * g / np.sqrt(acc[idx] + eps)

    t0 = time.perf_counter()
    for _ in range(triples):
        s, o = rng.integers(0, E, 2)
        r = rng.integers(0, R)
        sc, gs, gr, go = score_grad(ent[s], rel[r], ent[o])
        w = 1.0 / (1.0 + np.exp(sc)) if sc < 30 else 0.0  # sigmoid'(pos)
        adagrad(ent, ent_a, s, -w * gs)
        adagrad(rel, rel_a, r, -w * gr)
        adagrad(ent, ent_a, o, -w * go)
        for n in rng.integers(0, E, 2 * N):  # corrupt both sides
            sc, gs, gr, go = score_grad(ent[n], rel[r], ent[o])
            w = 1.0 / (1.0 + np.exp(-sc)) if sc > -30 else 0.0
            adagrad(ent, ent_a, n, w * gs)
            adagrad(rel, rel_a, r, w * gr)
            adagrad(ent, ent_a, o, w * go)
    return triples / (time.perf_counter() - t0)


def main():
    tput = bench_tpu()
    cpu = bench_cpu_reference_proxy()
    baseline = 64.0 * cpu  # 8 nodes x 8 worker threads
    print(json.dumps({
        "metric": "kge_complex_train_throughput",
        "value": round(tput, 1),
        "unit": "triples/sec (d=128, B=4096, N=32 negs, E=200k)",
        "vs_baseline": round(tput / baseline, 3),
    }))


if __name__ == "__main__":
    main()
