"""Headline benchmark: fused KGE ComplEx training throughput (triples/sec).

The reference's headline workload is ComplEx KGE training (README.md:140-159;
BASELINE.json north star: beat AdaPM-CPU 8-node wall-clock). This bench runs
the framework's fused train step (gather -> ComplEx score/grad -> AdaGrad ->
scatter-add on the sharded HBM pools, ops/fused.py) on the available device
and reports triples/sec.

vs_baseline: the reference publishes no in-tree numbers and its binary
cannot be built in this image (ZMQ/Boost/Eigen absent, installs forbidden —
BASELINE.md "Measured baselines"). The baseline is therefore MEASURED on
this host: a strong batched torch-CPU implementation of the same step,
per-core, scaled x64 for the paper's 8 nodes x 8 worker threads.
vs_baseline = tpu_triples_per_sec / (64 * torch_cpu_per_core_triples_per_sec).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

import numpy as np


def bench_tpu(E=200_000, R=1_000, d=128, B=4096, N=32, steps=50,
              warmup=5) -> float:
    import jax

    import adapm_tpu
    from adapm_tpu.config import SystemOptions
    from adapm_tpu.models import make_kge_loss
    from adapm_tpu.ops import DeviceRoutedRunner

    num_keys = E + R
    srv = adapm_tpu.setup(num_keys, 4 * d,
                          opts=SystemOptions(cache_slots_per_shard=1))
    w = srv.make_worker(0)
    rng = np.random.default_rng(0)
    # initialize in slabs to bound host memory
    slab = 50_000
    for lo in range(0, num_keys, slab):
        hi = min(lo + slab, num_keys)
        vals = rng.normal(size=(hi - lo, 4 * d)).astype(np.float32) * 0.1
        vals[:, 2 * d:] = 1e-6
        w.set(np.arange(lo, hi), vals)
    srv.block()

    # device-routed runner: routing tables mirrored in HBM, negatives drawn
    # in-program (Local sampling scheme on device) — the host ships only the
    # positive triple keys per step
    runner = DeviceRoutedRunner(
        srv, make_kge_loss("complex"),
        role_class={"s": 0, "r": 0, "o": 0, "neg": 0},
        role_dim={k: 2 * d for k in ("s", "r", "o", "neg")},
        neg_role="neg", neg_shape=(B, N),
        neg_population=np.arange(E))

    def batch():
        return {
            "s": rng.integers(0, E, B).astype(np.int64),
            "r": rng.integers(E, E + R, B).astype(np.int64),
            "o": rng.integers(0, E, B).astype(np.int64),
        }

    # Slope timing: some remote-attached TPU runtimes acknowledge
    # block_until_ready before work completes; only a value fetch truly
    # syncs, at a large fixed RTT. Timing two loop lengths and taking the
    # slope removes both the RTT and any warmup from the estimate.
    assert steps >= 4, "slope timing needs steps >= 4 (two loop lengths)"
    batches = [batch() for _ in range(4)]

    def timed(n: int) -> float:
        t0 = time.perf_counter()
        loss = None
        for i in range(n):
            loss = runner(batches[i % len(batches)], None, 0.1)
        float(loss)  # force completion of the whole donated chain
        return time.perf_counter() - t0

    for _ in range(warmup):
        runner(batches[0], None, 0.1)
    timed(1)
    t_short = timed(steps // 4)
    t_long = timed(steps)
    dt = (t_long - t_short) / (steps - steps // 4)
    srv.shutdown()
    return B / dt


def bench_cpu_torch(E=200_000, R=1_000, d=128, B=4096, N=32,
                    steps=3) -> float:
    """Measured CPU baseline: the same ComplEx+AdaGrad batch step written
    the way a competent torch user would (batched gathers, autograd on the
    gathered rows, index_add scatter) on this host's CPU. Stronger per core
    than the reference's per-triple C++ loop (kge.cc:437-531), so scaling
    it to the paper's cluster size gives a *conservative* baseline."""
    import torch

    # measure true single-core throughput (dividing an all-thread time by
    # the thread count would assume perfect intra-op scaling and inflate
    # vs_baseline on many-core hosts)
    torch.set_num_threads(1)
    torch.manual_seed(0)
    ent = torch.randn(E, 2 * d) * 0.1
    rel = torch.randn(R, 2 * d) * 0.1
    ent_a = torch.full((E, 2 * d), 1e-6)
    rel_a = torch.full((R, 2 * d), 1e-6)
    lr, eps = 0.1, 1e-10

    def cscore(s, r, o):
        sr, si = s[..., :d], s[..., d:]
        rr, ri = r[..., :d], r[..., d:]
        orr, oi = o[..., :d], o[..., d:]
        return (sr * rr * orr + si * rr * oi
                + sr * ri * oi - si * ri * orr).sum(-1)

    def step():
        s = torch.randint(0, E, (B,))
        r = torch.randint(0, R, (B,))
        o = torch.randint(0, E, (B,))
        n = torch.randint(0, E, (B, N))
        se = ent[s].requires_grad_(True)
        re_ = rel[r].requires_grad_(True)
        oe = ent[o].requires_grad_(True)
        ne = ent[n].requires_grad_(True)
        pos = cscore(se, re_, oe)
        neg = cscore(ne, re_.unsqueeze(1), oe.unsqueeze(1))
        loss = torch.nn.functional.softplus(-pos).sum() + \
            torch.nn.functional.softplus(neg).sum()
        loss.backward()

        def adagrad(table, acc, idx, g):
            acc.index_add_(0, idx, g * g)
            table.index_add_(0, idx, -lr * g / torch.sqrt(acc[idx] + eps))

        adagrad(ent, ent_a, s, se.grad)
        adagrad(rel, rel_a, r, re_.grad)
        adagrad(ent, ent_a, o, oe.grad)
        adagrad(ent, ent_a, n.reshape(-1), ne.grad.reshape(-1, 2 * d))

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(steps):
        step()
    per_step = (time.perf_counter() - t0) / steps
    return B / per_step


def main():
    tput = bench_tpu()
    # measured per-core CPU throughput of a strong batched torch
    # implementation of the same step; the paper's 8-node x 8-thread
    # cluster is modeled as 64 such cores (conservative: AdaPM's
    # per-triple C++ loop and network overhead are both slower per core).
    # The reference binary itself cannot be built in this image — its
    # ZMQ/Boost/Eigen dependencies are absent and installs are forbidden
    # (BASELINE.md "Measured baselines").
    cpu = bench_cpu_torch()
    baseline = 64.0 * cpu
    print(json.dumps({
        "metric": "kge_complex_train_throughput",
        "value": round(tput, 1),
        "unit": "triples/sec (d=128, B=4096, N=32 negs, E=200k)",
        "vs_baseline": round(tput / baseline, 3),
    }))


if __name__ == "__main__":
    main()
